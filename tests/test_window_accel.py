"""Device-accelerated windowed aggregation: equivalence with the host
tier, lateness, and cross-tier recovery."""

from datetime import datetime, timedelta, timezone

import numpy as np
import pytest

import bytewax_tpu.operators as op
import bytewax_tpu.operators.windowing as w
from bytewax_tpu.dataflow import Dataflow
from bytewax_tpu.engine.flatten import flatten
from bytewax_tpu.engine.window_accel import WindowAccelSpec
from bytewax_tpu.operators.windowing import (
    EventClock,
    SlidingWindower,
    TumblingWindower,
)
from bytewax_tpu.testing import TestingSink, TestingSource, run_main

ALIGN = datetime(2022, 1, 1, tzinfo=timezone.utc)


def _flow_count(inp, out, windower):
    clock = EventClock(
        ts_getter=lambda item: item[0],
        wait_for_system_duration=timedelta(seconds=5),
    )
    flow = Dataflow("test_df")
    s = op.input("inp", flow, TestingSource(inp, batch_size=64))
    wo = w.count_window("count", s, clock, windower, key=lambda item: item[1])
    op.output("out", wo.down, TestingSink(out))
    return flow


def _rand_events(n, n_keys=3, spread_s=600, seed=0):
    rng = np.random.RandomState(seed)
    # Mostly-increasing event times with jitter.
    base = np.sort(rng.randint(0, spread_s, size=n))
    return [
        (ALIGN + timedelta(seconds=int(s)), f"key{rng.randint(n_keys)}")
        for s in base
    ]


def test_count_window_is_annotated():
    flow = _flow_count(
        [], [], TumblingWindower(length=timedelta(minutes=1), align_to=ALIGN)
    )
    plan = flatten(flow)
    stateful = [o for o in plan.ops if o.name == "stateful_batch"]
    assert isinstance(stateful[0].conf.get("_accel"), WindowAccelSpec)


@pytest.mark.parametrize(
    "windower",
    [
        TumblingWindower(length=timedelta(minutes=1), align_to=ALIGN),
        SlidingWindower(
            length=timedelta(minutes=2),
            offset=timedelta(minutes=1),
            align_to=ALIGN,
        ),
    ],
    ids=["tumbling", "sliding"],
)
def test_count_window_device_matches_host(monkeypatch, windower):
    inp = _rand_events(500)

    def run(accel):
        monkeypatch.setenv("BYTEWAX_TPU_ACCEL", accel)
        out = []
        run_main(_flow_count(inp, out, windower))
        return sorted(out)

    device, host = run("1"), run("0")
    assert device == host


def test_count_window_benchmark_shape(monkeypatch):
    # The reference benchmark shape: timestamp items, 2 random keys,
    # 1-min tumbling windows — device vs host equivalence.
    monkeypatch.setenv("BYTEWAX_TPU_ACCEL", "1")
    import random

    def build(out):
        rand = random.Random(7)
        inp = [ALIGN + timedelta(seconds=i) for i in range(3000)]
        clock = EventClock(
            ts_getter=lambda x: x,
            wait_for_system_duration=timedelta(seconds=10),
        )
        windower = TumblingWindower(
            length=timedelta(minutes=1), align_to=ALIGN
        )
        flow = Dataflow("test_df")
        s = op.input("inp", flow, TestingSource(inp, batch_size=256))
        wo = w.count_window(
            "count", s, clock, windower, key=lambda _x: str(rand.randrange(2))
        )
        op.output("out", wo.down, TestingSink(out))
        return flow

    device = []
    run_main(build(device))
    monkeypatch.setenv("BYTEWAX_TPU_ACCEL", "0")
    host = []
    run_main(build(host))
    # Totals must match exactly; late-item routing may differ at batch
    # boundaries (documented), so compare window count sums.
    assert sum(c for _k, (_w, c) in device) == sum(
        c for _k, (_w, c) in host
    ) == 3000


def test_window_accel_late_items(monkeypatch):
    monkeypatch.setenv("BYTEWAX_TPU_ACCEL", "1")
    clock = EventClock(
        ts_getter=lambda item: item[0],
        wait_for_system_duration=timedelta(seconds=10),
    )
    windower = TumblingWindower(length=timedelta(minutes=1), align_to=ALIGN)
    inp = [
        (ALIGN + timedelta(seconds=120), "a"),
        (ALIGN + timedelta(seconds=1), "a"),  # far behind watermark
    ]
    down, late = [], []
    flow = Dataflow("test_df")
    s = op.input("inp", flow, TestingSource(inp))
    wo = w.count_window("count", s, clock, windower, key=lambda item: item[1])
    op.output("down", wo.down, TestingSink(down))
    op.output("late", wo.late, TestingSink(late))
    run_main(flow)
    assert len(late) == 1
    assert late[0][0] == "a"
    assert sum(c for _k, (_wid, c) in down) == 1


@pytest.mark.parametrize(
    "offsets_s, late_expected",
    [
        # Watermark jump first (wait=10s → watermark 110s), then a
        # borderline-old row IN THE SAME BATCH: late, post-item.
        ([120, 100], [100]),
        # Same rows, old one first: nothing has advanced the
        # watermark past it yet, so it is on time.
        ([100, 120], []),
        # Exactly AT the watermark (110 == 120 - 10): strict `<`
        # means on time.
        ([120, 110], []),
        # Just below: late.
        ([120, 109], [109]),
    ],
)
def test_window_accel_lateness_boundary(monkeypatch, offsets_s, late_expected):
    """Pin the in-batch lateness boundary: the device tier judges each
    row post-item against its key's running watermark, strict `<`,
    bit-identical to the host tier (`window_accel.py` semantics
    note)."""

    def run(accel):
        monkeypatch.setenv("BYTEWAX_TPU_ACCEL", accel)
        clock = EventClock(
            ts_getter=lambda item: item[0],
            wait_for_system_duration=timedelta(seconds=10),
        )
        windower = TumblingWindower(
            length=timedelta(minutes=1), align_to=ALIGN
        )
        inp = [(ALIGN + timedelta(seconds=s), "a") for s in offsets_s]
        down, late = [], []
        flow = Dataflow("test_df")
        # One delivered batch so the in-batch prefix-max path is
        # what judges the borderline row.
        s = op.input("inp", flow, TestingSource(inp, batch_size=len(inp)))
        wo = w.count_window(
            "count", s, clock, windower, key=lambda item: item[1]
        )
        op.output("down", wo.down, TestingSink(down))
        op.output("late", wo.late, TestingSink(late))
        run_main(flow)
        late_secs = sorted(
            int((v[0] - ALIGN).total_seconds()) for _k, (_wid, v) in late
        )
        counted = sum(c for _k, (_wid, c) in down)
        return late_secs, counted

    dev_late, dev_count = run("1")
    host_late, host_count = run("0")
    assert dev_late == host_late == late_expected
    assert dev_count == host_count == len(offsets_s) - len(late_expected)


def test_window_accel_cross_tier_recovery(tmp_path, monkeypatch):
    from bytewax_tpu.recovery import RecoveryConfig, init_db_dir

    init_db_dir(tmp_path, 1)
    rc = RecoveryConfig(str(tmp_path))
    clock = EventClock(
        ts_getter=lambda item: item[0],
        wait_for_system_duration=timedelta(days=999),
    )
    windower = TumblingWindower(length=timedelta(minutes=1), align_to=ALIGN)
    inp = [
        (ALIGN + timedelta(seconds=1), "a"),
        (ALIGN + timedelta(seconds=2), "a"),
        TestingSource.ABORT(),
        (ALIGN + timedelta(seconds=3), "a"),
    ]
    out = []
    flow = Dataflow("test_df")
    s = op.input("inp", flow, TestingSource(inp))
    wo = w.count_window("count", s, clock, windower, key=lambda item: item[1])
    op.output("out", wo.down, TestingSink(out))

    # Crash on the device tier, resume on the host tier.
    monkeypatch.setenv("BYTEWAX_TPU_ACCEL", "1")
    run_main(flow, epoch_interval=timedelta(0), recovery_config=rc)
    assert out == []
    monkeypatch.setenv("BYTEWAX_TPU_ACCEL", "0")
    run_main(flow, epoch_interval=timedelta(0), recovery_config=rc)
    assert out == [("a", (0, 3))]


def test_count_window_columnar(monkeypatch):
    # Columnar event batches (key + ts columns) count with no
    # per-item Python; results match the itemized device path.
    monkeypatch.setenv("BYTEWAX_TPU_ACCEL", "1")
    from bytewax_tpu.engine.arrays import ArrayBatch
    from tests.test_xla import ArraySource

    n = 5000
    rng = np.random.RandomState(3)
    secs = np.sort(rng.randint(0, 600, size=n))
    keys = np.array([f"key{k}" for k in rng.randint(0, 3, size=n)])
    ts = (
        np.datetime64(ALIGN.replace(tzinfo=None), "us")
        + secs.astype("timedelta64[s]")
    )
    batches = [
        ArrayBatch({"key": keys[i : i + 512], "ts": ts[i : i + 512]})
        for i in range(0, n, 512)
    ]

    clock = EventClock(
        ts_getter=lambda item: item,  # unused on the columnar path
        wait_for_system_duration=timedelta(seconds=5),
    )
    windower = TumblingWindower(length=timedelta(minutes=1), align_to=ALIGN)
    out = []
    flow = Dataflow("test_df")
    s = op.input("inp", flow, ArraySource(batches))
    wo = w.count_window("count", s, clock, windower, key=lambda item: item)
    op.output("out", wo.down, TestingSink(out))
    run_main(flow)

    assert sum(c for _k, (_w, c) in out) == n
    # Spot-check one window against numpy.
    k0w0 = [
        c for k, (wid, c) in out if k == "key0" and wid == 0
    ]
    expect = int(((keys == "key0") & (secs < 60)).sum())
    assert k0w0 == [expect]


def test_columnar_batches_degrade_on_host_tier(monkeypatch):
    # With accel disabled, {'key','ts'} columnar batches must still
    # key and count correctly through the host tier.
    monkeypatch.setenv("BYTEWAX_TPU_ACCEL", "0")
    from bytewax_tpu.engine.arrays import ArrayBatch
    from tests.test_xla import ArraySource

    secs = np.array([1, 2, 61])
    keys = np.array(["a", "b", "a"])
    ts = (
        np.datetime64(ALIGN.replace(tzinfo=None), "us")
        + secs.astype("timedelta64[s]")
    )
    batches = [ArrayBatch({"key": keys, "ts": ts})]
    clock = EventClock(
        ts_getter=lambda item: item,
        wait_for_system_duration=timedelta(seconds=5),
    )
    out = []
    flow = Dataflow("test_df")
    s = op.input("inp", flow, ArraySource(batches))
    wo = w.count_window(
        "count",
        s,
        clock,
        TumblingWindower(length=timedelta(minutes=1), align_to=ALIGN),
        key=lambda item: item,
    )
    op.output("out", wo.down, TestingSink(out))
    run_main(flow)
    assert sorted(out) == [("a", (0, 1)), ("a", (1, 1)), ("b", (0, 1))]


def test_windowed_sum_columnar_matches_host(monkeypatch):
    # Numeric windowed folds on columnar key/ts/value batches: device
    # result must match the host tier folding the same rows as items.
    from bytewax_tpu import xla
    from bytewax_tpu.engine.arrays import ArrayBatch
    from tests.test_xla import ArraySource

    n = 4000
    rng = np.random.RandomState(5)
    secs = np.sort(rng.randint(0, 600, size=n))
    keys = np.array([f"key{k}" for k in rng.randint(0, 3, size=n)])
    vals = rng.randn(n).astype(np.float64).round(3)
    ts = (
        np.datetime64(ALIGN.replace(tzinfo=None), "us")
        + secs.astype("timedelta64[s]")
    )
    windower = TumblingWindower(length=timedelta(minutes=1), align_to=ALIGN)

    def run_device():
        monkeypatch.setenv("BYTEWAX_TPU_ACCEL", "1")
        batches = [
            ArrayBatch(
                {
                    "key": keys[i : i + 512],
                    "ts": ts[i : i + 512],
                    "value": vals[i : i + 512],
                }
            )
            for i in range(0, n, 512)
        ]
        clock = EventClock(
            ts_getter=lambda item: item,
            wait_for_system_duration=timedelta(seconds=30),
        )
        out = []
        flow = Dataflow("test_df")
        s = op.input("inp", flow, ArraySource(batches))
        wo = w.reduce_window("sum", s, clock, windower, xla.SUM)
        op.output("out", wo.down, TestingSink(out))
        run_main(flow)
        return out

    # Numpy oracle: input is time-sorted so nothing is late; expected
    # is a plain groupby-sum over (key, window).
    expected = {}
    for k, s_, v in zip(keys.tolist(), secs.tolist(), vals.tolist()):
        wid = s_ // 60
        expected[(k, wid)] = expected.get((k, wid), 0.0) + v

    device = {(k, wid): v for k, (wid, v) in run_device()}
    assert set(device) == set(expected)
    for key in expected:
        assert abs(device[key] - expected[key]) < 1e-3, key


def test_windowed_sum_itemized_falls_back_to_host(monkeypatch):
    # Itemized deliveries into a numeric windowed fold run host-tier.
    from bytewax_tpu import xla

    monkeypatch.setenv("BYTEWAX_TPU_ACCEL", "1")
    windower = TumblingWindower(length=timedelta(minutes=1), align_to=ALIGN)
    inp = [
        ("k", (ALIGN + timedelta(seconds=1), 2.0)),
        ("k", (ALIGN + timedelta(seconds=2), 3.0)),
    ]
    out = []
    flow = Dataflow("test_df")
    s = op.input("inp", flow, TestingSource(inp))
    vs = op.map_value("unpack", s, lambda pair: pair[1])
    clock2 = EventClock(
        ts_getter=_TsFromPairStream(inp),
        wait_for_system_duration=timedelta(seconds=5),
    )
    wo = w.reduce_window("sum", vs, clock2, windower, xla.SUM)
    op.output("out", wo.down, TestingSink(out))
    run_main(flow)
    assert out == [("k", (0, 5.0))]


class _TsFromPairStream:
    """Host-tier ts getter for bare values in this test."""

    def __init__(self, inp):
        self._ts = {v: t for _k, (t, v) in inp}

    def __call__(self, v):
        return self._ts[v]


def test_windowed_fold_nonconforming_columnar_falls_back(monkeypatch):
    # A columnar batch with ts but no value column must fall back to
    # the host tier (degrading to keyed items), not crash.
    from bytewax_tpu import xla
    from bytewax_tpu.engine.arrays import ArrayBatch
    from tests.test_xla import ArraySource

    monkeypatch.setenv("BYTEWAX_TPU_ACCEL", "1")
    ts = (
        np.datetime64(ALIGN.replace(tzinfo=None), "us")
        + np.array([1, 2]).astype("timedelta64[s]")
    )
    batches = [ArrayBatch({"key": np.array(["k", "k"]), "ts": ts})]
    clock = EventClock(
        ts_getter=lambda v: v,  # host degrade: value IS the timestamp
        wait_for_system_duration=timedelta(seconds=5),
    )
    windower = TumblingWindower(length=timedelta(minutes=1), align_to=ALIGN)
    out = []
    flow = Dataflow("test_df")
    s = op.input("inp", flow, ArraySource(batches))
    wo = w.reduce_window("max", s, clock, windower, xla.MAX)
    op.output("out", wo.down, TestingSink(out))
    run_main(flow)
    assert out == [("k", (0, ALIGN + timedelta(seconds=2)))]


def test_high_cardinality_windowed_count(monkeypatch):
    # 20k keys with open windows: the per-batch due check must stay
    # vectorized (this is a smoke bound, not a benchmark).
    import time

    monkeypatch.setenv("BYTEWAX_TPU_ACCEL", "1")
    from bytewax_tpu.engine.arrays import ArrayBatch
    from tests.test_xla import ArraySource

    n_keys = 20_000
    rows_per_batch = n_keys
    n_batches = 5
    keys = np.array([f"key{i:05d}" for i in range(n_keys)])
    batches = []
    for b in range(n_batches):
        ts = (
            np.datetime64(ALIGN.replace(tzinfo=None), "us")
            + np.full(rows_per_batch, b, dtype=np.int64).astype(
                "timedelta64[s]"
            )
        )
        batches.append(ArrayBatch({"key": keys, "ts": ts}))

    clock = EventClock(
        ts_getter=lambda item: item,
        wait_for_system_duration=timedelta(seconds=60),
    )
    windower = TumblingWindower(length=timedelta(minutes=1), align_to=ALIGN)
    out = []
    flow = Dataflow("test_df")
    s = op.input("inp", flow, ArraySource(batches))
    wo = w.count_window("count", s, clock, windower, key=lambda item: item)
    op.output("out", wo.down, TestingSink(out))
    t0 = time.monotonic()
    run_main(flow)
    elapsed = time.monotonic() - t0
    assert len(out) == n_keys
    assert all(c == n_batches for _k, (_w, c) in out)
    assert elapsed < 30, f"high-cardinality run too slow: {elapsed:.1f}s"


def test_windowed_sum_columnar_degrades_on_host_tier(monkeypatch):
    # {'key','ts','value'} batches must degrade to (key, TsValue)
    # items so the host-tier oracle (BYTEWAX_TPU_ACCEL=0) keys, times,
    # and folds them correctly.
    monkeypatch.setenv("BYTEWAX_TPU_ACCEL", "0")
    from bytewax_tpu import xla
    from bytewax_tpu.engine.arrays import ArrayBatch
    from tests.test_xla import ArraySource

    secs = np.array([1, 2, 61])
    keys = np.array(["a", "b", "a"])
    vals = np.array([2.0, 5.0, 7.0])
    ts = (
        np.datetime64(ALIGN.replace(tzinfo=None), "us")
        + secs.astype("timedelta64[s]")
    )
    batches = [ArrayBatch({"key": keys, "ts": ts, "value": vals})]
    clock = EventClock(
        ts_getter=xla.column_ts,
        wait_for_system_duration=timedelta(seconds=5),
    )
    out = []
    flow = Dataflow("test_df")
    s = op.input("inp", flow, ArraySource(batches))
    wo = w.reduce_window(
        "sum",
        s,
        clock,
        TumblingWindower(length=timedelta(minutes=1), align_to=ALIGN),
        xla.SUM,
    )
    op.output("out", wo.down, TestingSink(out))
    run_main(flow)
    assert sorted(out) == [("a", (0, 2.0)), ("a", (1, 7.0)), ("b", (0, 5.0))]


def test_ts_value_degrade_shapes():
    # The {'key','ts','value'} to_pylist convention: (key, TsValue)
    # pairs whose payload folds as a float and carries .ts, applying
    # any fixed-point value_scale; survives pickling (cluster ship).
    import pickle

    from bytewax_tpu.engine.arrays import ArrayBatch, TsValue, column_ts

    ts = (
        np.datetime64(ALIGN.replace(tzinfo=None), "us")
        + np.array([1, 2]).astype("timedelta64[s]")
    )
    ab = ArrayBatch(
        {
            "key": np.array(["a", "b"]),
            "ts": ts,
            "value": np.array([25, -5], dtype=np.int16),
        },
        value_scale=0.1,
    )
    items = ab.to_pylist()
    assert [k for k, _v in items] == ["a", "b"]
    assert [float(v) for _k, v in items] == [2.5, -0.5]
    assert [column_ts(v) for _k, v in items] == [
        ALIGN + timedelta(seconds=1),
        ALIGN + timedelta(seconds=2),
    ]
    v2 = pickle.loads(pickle.dumps(items[0][1]))
    assert isinstance(v2, TsValue)
    assert (float(v2), v2.ts) == (2.5, ALIGN + timedelta(seconds=1))


def test_window_accel_host_to_device_recovery(tmp_path, monkeypatch):
    # An ordered=True host-tier window logic keeps on-time values
    # whose ts is ahead of the watermark in its snapshot `queue`;
    # resuming that snapshot on the device tier must replay them into
    # their windows, not drop them.
    from bytewax_tpu import xla
    from bytewax_tpu.recovery import RecoveryConfig, init_db_dir

    init_db_dir(tmp_path, 1)
    rc = RecoveryConfig(str(tmp_path))
    ts_map = {
        2.0: ALIGN + timedelta(seconds=1),
        3.0: ALIGN + timedelta(seconds=2),
        4.0: ALIGN + timedelta(seconds=3),
    }
    clock = EventClock(
        ts_getter=lambda v: ts_map[v],
        wait_for_system_duration=timedelta(days=999),
    )
    windower = TumblingWindower(length=timedelta(minutes=1), align_to=ALIGN)
    inp = [
        ("k", 2.0),
        ("k", 3.0),
        TestingSource.ABORT(),
        ("k", 4.0),
    ]
    out = []
    flow = Dataflow("test_df")
    s = op.input("inp", flow, TestingSource(inp))
    # fold_window (not reduce_window) because only ordered=True logics
    # carry a queue, and reduce_window lowers with ordered=False.
    wo = w.fold_window("sum", s, clock, windower, lambda: 0, xla.SUM, xla.SUM)
    op.output("out", wo.down, TestingSink(out))

    # Crash on the host tier (pending values live in `queue`), resume
    # on the device tier.
    monkeypatch.setenv("BYTEWAX_TPU_ACCEL", "0")
    run_main(flow, epoch_interval=timedelta(0), recovery_config=rc)
    assert out == []
    monkeypatch.setenv("BYTEWAX_TPU_ACCEL", "1")
    run_main(flow, epoch_interval=timedelta(0), recovery_config=rc)
    assert out == [("k", (0, 9))]


@pytest.mark.parametrize("kind", ["mean", "stats"])
def test_windowed_mean_stats_device_matches_host(monkeypatch, kind):
    # mean/stats windowed folds lower to the device slot table; output
    # must match the host tier folding the same columnar rows.
    import bytewax_tpu.operators.windowing as w2
    from bytewax_tpu import xla
    from bytewax_tpu.engine.arrays import ArrayBatch
    from tests.test_xla import ArraySource

    n = 3000
    rng = np.random.RandomState(11)
    secs = np.sort(rng.randint(0, 300, size=n))
    keys = np.array([f"key{k}" for k in rng.randint(0, 3, size=n)])
    vals = (rng.randn(n) * 5).round(2)
    ts = (
        np.datetime64(ALIGN.replace(tzinfo=None), "us")
        + secs.astype("timedelta64[s]")
    )
    windower = TumblingWindower(length=timedelta(minutes=1), align_to=ALIGN)
    op_fn = w2.mean_window if kind == "mean" else w2.stats_window

    def run(accel):
        monkeypatch.setenv("BYTEWAX_TPU_ACCEL", accel)
        batches = [
            ArrayBatch(
                {
                    "key": keys[i : i + 512],
                    "ts": ts[i : i + 512],
                    "value": vals[i : i + 512],
                }
            )
            for i in range(0, n, 512)
        ]
        clock = EventClock(
            ts_getter=xla.column_ts,
            wait_for_system_duration=timedelta(seconds=30),
        )
        out = []
        flow = Dataflow("test_df")
        s = op.input("inp", flow, ArraySource(batches))
        wo = op_fn(kind, s, clock, windower)
        op.output("out", wo.down, TestingSink(out))
        run_main(flow)
        return sorted(out)

    device, host = run("1"), run("0")
    assert [kv[0] for kv in device] == [kv[0] for kv in host]
    for (k, (wid_d, v_d)), (_k, (wid_h, v_h)) in zip(device, host):
        assert wid_d == wid_h
        np.testing.assert_allclose(v_d, v_h, rtol=1e-4, err_msg=k)

    # And against a numpy oracle (mean case).
    if kind == "mean":
        expected = {}
        for k, s_, v in zip(keys.tolist(), secs.tolist(), vals.tolist()):
            expected.setdefault((k, s_ // 60), []).append(v)
        got = {(k, wid): v for k, (wid, v) in device}
        assert set(got) == set(expected)
        for key2, rows in expected.items():
            np.testing.assert_allclose(
                got[key2], np.mean(rows), rtol=1e-4, err_msg=str(key2)
            )


def test_fold_window_with_mean_marker_is_annotated():
    # The VERDICT bar: fold_window(..., MEAN)-style flows lower.
    from bytewax_tpu import xla
    from bytewax_tpu.engine.flatten import flatten
    from bytewax_tpu.engine.window_accel import WindowAccelSpec

    clock = EventClock(
        ts_getter=lambda v: ALIGN, wait_for_system_duration=timedelta(0)
    )
    windower = TumblingWindower(length=timedelta(minutes=1), align_to=ALIGN)
    flow = Dataflow("test_df")
    s = op.input("inp", flow, TestingSource([]))
    wo = w.fold_window(
        "m", s, clock, windower, xla.MEAN.make_acc, xla.MEAN, xla.MEAN.merge
    )
    op.output("out", wo.down, TestingSink([]))
    plan = flatten(flow)
    stateful = [o for o in plan.ops if o.name == "stateful_batch"]
    spec = stateful[0].conf.get("_accel")
    assert isinstance(spec, WindowAccelSpec)
    assert spec.kind == "mean"


def test_mean_window_cross_tier_recovery(tmp_path, monkeypatch):
    # mean windows crash on the device tier and resume on the host
    # tier (and the accumulator format crosses over).
    import bytewax_tpu.operators.windowing as w2
    from bytewax_tpu.recovery import RecoveryConfig, init_db_dir

    init_db_dir(tmp_path, 1)
    rc = RecoveryConfig(str(tmp_path))
    ts_map = {
        2.0: ALIGN + timedelta(seconds=1),
        4.0: ALIGN + timedelta(seconds=2),
        9.0: ALIGN + timedelta(seconds=3),
    }
    clock = EventClock(
        ts_getter=lambda v: ts_map[v],
        wait_for_system_duration=timedelta(days=999),
    )
    windower = TumblingWindower(length=timedelta(minutes=1), align_to=ALIGN)
    inp = [
        ("k", 2.0),
        ("k", 4.0),
        TestingSource.ABORT(),
        ("k", 9.0),
    ]
    out = []
    flow = Dataflow("test_df")
    s = op.input("inp", flow, TestingSource(inp))
    wo = w2.mean_window("mean", s, clock, windower)
    op.output("out", wo.down, TestingSink(out))

    monkeypatch.setenv("BYTEWAX_TPU_ACCEL", "1")
    run_main(flow, epoch_interval=timedelta(0), recovery_config=rc)
    assert out == []
    monkeypatch.setenv("BYTEWAX_TPU_ACCEL", "0")
    run_main(flow, epoch_interval=timedelta(0), recovery_config=rc)
    assert out == [("k", (0, 5.0))]


def test_count_window_dict_encoded_columnar(monkeypatch):
    # {'key_id','ts'} + vocab batches count on device without string
    # sorting; results match the string-keyed columnar path and the
    # host tier (which degrades through the vocab).
    from bytewax_tpu.engine.arrays import ArrayBatch
    from tests.test_xla import ArraySource

    n = 4000
    rng = np.random.RandomState(9)
    secs = np.sort(rng.randint(0, 600, size=n))
    ids = rng.randint(0, 5, size=n).astype(np.int32)
    vocab = np.array([f"key{k}" for k in range(5)])
    ts = (
        np.datetime64(ALIGN.replace(tzinfo=None), "us")
        + secs.astype("timedelta64[s]")
    )

    def build(out, encoded):
        if encoded:
            batches = [
                ArrayBatch(
                    {"key_id": ids[i : i + 512], "ts": ts[i : i + 512]},
                    key_vocab=vocab,
                )
                for i in range(0, n, 512)
            ]
        else:
            batches = [
                ArrayBatch(
                    {"key": vocab[ids[i : i + 512]], "ts": ts[i : i + 512]}
                )
                for i in range(0, n, 512)
            ]
        clock = EventClock(
            ts_getter=lambda item: item,
            wait_for_system_duration=timedelta(seconds=5),
        )
        windower = TumblingWindower(
            length=timedelta(minutes=1), align_to=ALIGN
        )
        flow = Dataflow("test_df")
        s = op.input("inp", flow, ArraySource(batches))
        wo = w.count_window("count", s, clock, windower, key=lambda x: x)
        op.output("out", wo.down, TestingSink(out))
        return flow

    monkeypatch.setenv("BYTEWAX_TPU_ACCEL", "1")
    enc, strs = [], []
    run_main(build(enc, True))
    run_main(build(strs, False))
    assert sorted(enc) == sorted(strs)
    monkeypatch.setenv("BYTEWAX_TPU_ACCEL", "0")
    host = []
    run_main(build(host, True))
    assert sorted(enc) == sorted(host)
    assert sum(c for _k, (_w, c) in enc) == n


def test_windowed_sum_dict_encoded_matches_host(monkeypatch):
    # {'key_id','ts','value'} + vocab: numeric windowed folds on the
    # dict-encoded fast path match the host tier degrade.
    from bytewax_tpu import xla
    from bytewax_tpu.engine.arrays import ArrayBatch
    from tests.test_xla import ArraySource

    n = 3000
    rng = np.random.RandomState(10)
    secs = np.sort(rng.randint(0, 300, size=n))
    ids = rng.randint(0, 4, size=n).astype(np.int32)
    vocab = np.array([f"s{k}" for k in range(4)])
    vals = (rng.randn(n) * 4).round(2)
    ts = (
        np.datetime64(ALIGN.replace(tzinfo=None), "us")
        + secs.astype("timedelta64[s]")
    )

    def run(accel):
        monkeypatch.setenv("BYTEWAX_TPU_ACCEL", accel)
        batches = [
            ArrayBatch(
                {
                    "key_id": ids[i : i + 512],
                    "ts": ts[i : i + 512],
                    "value": vals[i : i + 512],
                },
                key_vocab=vocab,
            )
            for i in range(0, n, 512)
        ]
        clock = EventClock(
            ts_getter=xla.column_ts,
            wait_for_system_duration=timedelta(seconds=30),
        )
        windower = TumblingWindower(
            length=timedelta(minutes=1), align_to=ALIGN
        )
        out = []
        flow = Dataflow("test_df")
        s = op.input("inp", flow, ArraySource(batches))
        wo = w.reduce_window("sum", s, clock, windower, xla.SUM)
        op.output("out", wo.down, TestingSink(out))
        run_main(flow)
        return sorted(out)

    device, host = run("1"), run("0")
    assert [kv[0] for kv in device] == [kv[0] for kv in host]
    for (k, (wd, vd)), (_k, (wh, vh)) in zip(device, host):
        assert wd == wh
        # Device accumulates in float32.
        np.testing.assert_allclose(vd, vh, rtol=1e-4, err_msg=k)


def test_windowed_vocab_must_extend(monkeypatch):
    # Swapping in an unrelated vocabulary between batches must raise,
    # not silently remap ids.
    monkeypatch.setenv("BYTEWAX_TPU_ACCEL", "1")
    from bytewax_tpu.engine.window_accel import (
        DeviceWindowAggState,
        WindowAccelSpec,
    )
    from bytewax_tpu.engine.arrays import ArrayBatch

    spec = WindowAccelSpec(
        "count",
        lambda x: x,
        ALIGN,
        timedelta(minutes=1),
        timedelta(minutes=1),
        timedelta(seconds=5),
    )
    st = DeviceWindowAggState(spec)
    ts = (
        np.datetime64(ALIGN.replace(tzinfo=None), "us")
        + np.array([1, 2]).astype("timedelta64[s]")
    )
    v1 = np.array(["a", "b"])
    st.on_batch_columnar(
        ArrayBatch({"key_id": np.array([0, 1]), "ts": ts}, key_vocab=v1)
    )
    v2 = np.array(["x", "b"])
    with pytest.raises(TypeError, match="append-only"):
        st.on_batch_columnar(
            ArrayBatch({"key_id": np.array([0, 1]), "ts": ts}, key_vocab=v2)
        )


def test_windowed_sum_mixed_columnar_then_itemized(monkeypatch):
    # Once device state exists (from columnar batches), later
    # itemized deliveries flow through the device fold via the ts
    # getter — a mixed stream must match the host tier end to end.
    from bytewax_tpu import xla
    from bytewax_tpu.engine.arrays import ArrayBatch
    from bytewax_tpu.inputs import DynamicSource, StatelessSourcePartition

    ts0 = ALIGN + timedelta(seconds=1)
    ts1 = ALIGN + timedelta(seconds=2)
    ts2 = ALIGN + timedelta(seconds=70)
    col = ArrayBatch(
        {
            "key": np.array(["a", "b"]),
            "ts": np.array(
                [np.datetime64(ts0.replace(tzinfo=None), "us"),
                 np.datetime64(ts1.replace(tzinfo=None), "us")]
            ),
            "value": np.array([2.0, 5.0]),
        }
    )
    itemized = [
        ("a", xla.TsValue(3.0, ts1)),
        ("b", xla.TsValue(7.0, ts2)),
    ]

    class _P(StatelessSourcePartition):
        def __init__(self):
            self._batches = [col, itemized]

        def next_batch(self):
            if not self._batches:
                raise StopIteration()
            return self._batches.pop(0)

    class Src(DynamicSource):
        def build(self, step_id, wi, wc):
            p = _P()
            if wi != 0:
                p._batches = []
            return p

    def run(accel):
        monkeypatch.setenv("BYTEWAX_TPU_ACCEL", accel)
        clock = EventClock(
            ts_getter=xla.column_ts,
            wait_for_system_duration=timedelta(seconds=5),
        )
        windower = TumblingWindower(
            length=timedelta(minutes=1), align_to=ALIGN
        )
        out = []
        flow = Dataflow("test_df")
        s = op.input("inp", flow, Src())
        wo = w.reduce_window("sum", s, clock, windower, xla.SUM)
        op.output("out", wo.down, TestingSink(out))
        run_main(flow)
        return sorted(out)

    device, host = run("1"), run("0")
    assert device == host == [
        ("a", (0, 5.0)),
        ("b", (0, 5.0)),
        ("b", (1, 7.0)),
    ]


def test_windowed_fallback_boundary_then_columnar(monkeypatch):
    # Itemized rows BEFORE any device state permanently fall the step
    # back to the host tier; columnar batches arriving afterwards must
    # still fold correctly (degraded), matching an all-host run.
    from bytewax_tpu import xla
    from bytewax_tpu.engine.arrays import ArrayBatch
    from bytewax_tpu.inputs import DynamicSource, StatelessSourcePartition

    ts0 = ALIGN + timedelta(seconds=1)
    itemized = [("a", xla.TsValue(2.0, ts0))]
    col = ArrayBatch(
        {
            "key": np.array(["a"]),
            "ts": np.array([np.datetime64(ts0.replace(tzinfo=None), "us")]),
            "value": np.array([3.0]),
        }
    )

    class _P(StatelessSourcePartition):
        def __init__(self):
            self._batches = [itemized, col]

        def next_batch(self):
            if not self._batches:
                raise StopIteration()
            return self._batches.pop(0)

    class Src(DynamicSource):
        def build(self, step_id, wi, wc):
            p = _P()
            if wi != 0:
                p._batches = []
            return p

    def run(accel):
        monkeypatch.setenv("BYTEWAX_TPU_ACCEL", accel)
        clock = EventClock(
            ts_getter=xla.column_ts,
            wait_for_system_duration=timedelta(seconds=5),
        )
        windower = TumblingWindower(
            length=timedelta(minutes=1), align_to=ALIGN
        )
        out = []
        flow = Dataflow("test_df")
        s = op.input("inp", flow, Src())
        wo = w.reduce_window("sum", s, clock, windower, xla.SUM)
        op.output("out", wo.down, TestingSink(out))
        run_main(flow)
        return sorted(out)

    device, host = run("1"), run("0")
    assert device == host == [("a", (0, 5.0))]


def test_dict_encoded_window_cross_tier_recovery(tmp_path, monkeypatch):
    # Dict-encoded windowed batches crash on the device tier and
    # resume on the host tier (and the vocab re-syncs after resume on
    # the device tier).
    from bytewax_tpu import xla
    from bytewax_tpu.engine.arrays import ArrayBatch
    from bytewax_tpu.inputs import FixedPartitionedSource, StatefulSourcePartition
    from bytewax_tpu.recovery import RecoveryConfig, init_db_dir

    init_db_dir(tmp_path, 1)
    rc = RecoveryConfig(str(tmp_path))
    vocab = np.array(["a", "b"])
    base = np.datetime64(ALIGN.replace(tzinfo=None), "us")

    def batch(ids, secs, vals):
        return ArrayBatch(
            {
                "key_id": np.asarray(ids, dtype=np.int32),
                "ts": base + np.asarray(secs).astype("timedelta64[s]"),
                "value": np.asarray(vals, dtype=np.float64),
            },
            key_vocab=vocab,
        )

    crashed: list = []  # the crash marker fires once, like ABORT

    class _Part(StatefulSourcePartition):
        def __init__(self, resume):
            self._i = resume or 0
            self._batches = [
                batch([0, 1], [1, 2], [2.0, 5.0]),
                None,  # crash marker
                batch([0, 1], [3, 4], [3.0, 7.0]),
            ]

        def next_batch(self):
            while True:
                if self._i >= len(self._batches):
                    raise StopIteration()
                b = self._batches[self._i]
                self._i += 1
                if b is None:
                    if not crashed:
                        crashed.append(True)
                        from bytewax_tpu.inputs import AbortExecution

                        raise AbortExecution()
                    continue
                return b

        def snapshot(self):
            return self._i

    class Src(FixedPartitionedSource):
        def list_parts(self):
            return ["p0"]

        def build_part(self, step_id, name, resume):
            return _Part(resume)

    def build(out):
        clock = EventClock(
            ts_getter=xla.column_ts,
            wait_for_system_duration=timedelta(days=999),
        )
        windower = TumblingWindower(
            length=timedelta(minutes=1), align_to=ALIGN
        )
        flow = Dataflow("test_df")
        s = op.input("inp", flow, Src())
        wo = w.reduce_window("sum", s, clock, windower, xla.SUM)
        op.output("out", wo.down, TestingSink(out))
        return flow

    out: list = []
    monkeypatch.setenv("BYTEWAX_TPU_ACCEL", "1")
    # The crash marker only fires on the first execution: resumes skip
    # it because the partition snapshot is already past its index.
    run_main(build(out), epoch_interval=timedelta(0), recovery_config=rc)
    assert out == []
    monkeypatch.setenv("BYTEWAX_TPU_ACCEL", "0")
    run_main(build(out), epoch_interval=timedelta(0), recovery_config=rc)
    assert sorted(out) == [("a", (0, 5.0)), ("b", (0, 12.0))]


def test_key_id_without_vocab_raises_clearly():
    # A key_id column invokes the dict convention; forgetting the
    # vocab must be a clear error, not silently mis-keyed rows.
    from bytewax_tpu.engine.arrays import ArrayBatch

    ts = (
        np.datetime64(ALIGN.replace(tzinfo=None), "us")
        + np.array([1]).astype("timedelta64[s]")
    )
    for cols in (
        {"key_id": np.array([0]), "ts": ts},
        {"key_id": np.array([0]), "ts": ts, "value": np.array([1.0])},
        {"key_id": np.array([0]), "value": np.array([1.0])},
    ):
        with pytest.raises(TypeError, match="key_vocab"):
            ArrayBatch(cols).to_pylist()


def test_itemized_promotion_unit_matches_per_item_path():
    """on_batch_items (native wa_encode promotion) must produce the
    same events and snapshots as the per-item on_batch path for both
    row shapes: (key, datetime) counts and (key, TsValue) sums."""
    from bytewax_tpu import xla
    from bytewax_tpu.engine.window_accel import (
        DeviceWindowAggState,
        WindowAccelSpec,
    )

    pytest.importorskip("bytewax_tpu.native")
    from bytewax_tpu.native import wa_encode as _probe

    if _probe([], {}, np.empty(0, np.int32), np.empty(0), np.empty(0)) is None:
        pytest.skip("native toolchain unavailable")

    def specs(kind, getter):
        return WindowAccelSpec(
            kind,
            getter,
            ALIGN,
            timedelta(minutes=1),
            timedelta(minutes=1),
            timedelta(0),
        )

    def run(ingest):
        # on_batch* return (late_events, device_phase); materialize
        # the deferred phase to get the full event stream.
        late, phase = ingest
        closes, _hint = phase()
        return late + closes

    # Count shape: values ARE the timestamps.
    items = [
        ("a", ALIGN + timedelta(seconds=s)) for s in (1, 2, 61, 150)
    ] + [("b", ALIGN + timedelta(seconds=5))]
    st_promo = specs("count", lambda x: x).make_state()
    st_items = specs("count", lambda x: x).make_state()
    ev_promo = st_promo.on_batch_items(list(items))
    assert ev_promo is not None
    ev_items = st_items.on_batch(
        [k for k, _ in items], [v for _, v in items]
    )
    assert run(ev_promo) == run(ev_items)
    assert dict(st_promo.snapshots_for(["a", "b"])).keys() == dict(
        st_items.snapshots_for(["a", "b"])
    ).keys()

    # TsValue shape: floats carrying their event timestamp.
    rows = [
        ("a", xla.TsValue(2.0, ALIGN + timedelta(seconds=1))),
        ("a", xla.TsValue(3.0, ALIGN + timedelta(seconds=2))),
        ("b", xla.TsValue(7.0, ALIGN + timedelta(seconds=61))),
    ]
    st2_promo = specs("sum", xla.column_ts).make_state()
    st2_items = specs("sum", xla.column_ts).make_state()
    ev2_promo = st2_promo.on_batch_items(list(rows))
    assert ev2_promo is not None
    ev2_items = st2_items.on_batch(
        [k for k, _ in rows], [v for _, v in rows]
    )
    assert run(ev2_promo) == run(ev2_items)


def test_itemized_promotion_rejects_disagreeing_getter():
    """A ts_getter that does NOT read the row's own timestamp must
    force the per-item path (NonNumericValues), not silently use the
    row timestamp."""
    from bytewax_tpu.engine.window_accel import WindowAccelSpec
    from bytewax_tpu.engine.xla import NonNumericValues
    from bytewax_tpu.native import wa_encode as _probe

    if _probe([], {}, np.empty(0, np.int32), np.empty(0), np.empty(0)) is None:
        pytest.skip("native toolchain unavailable")

    shifted = WindowAccelSpec(
        "count",
        lambda x: x + timedelta(hours=1),  # disagrees with the row ts
        ALIGN,
        timedelta(minutes=1),
        timedelta(minutes=1),
        timedelta(0),
    ).make_state()
    with pytest.raises(NonNumericValues):
        shifted.on_batch_items([("a", ALIGN + timedelta(seconds=1))])


def test_itemized_promotion_rejects_non_utc():
    """Non-UTC tzinfo rows take the per-item path (its .timestamp()
    handles any tz); the native promotion must refuse them."""
    from bytewax_tpu.engine.window_accel import WindowAccelSpec
    from bytewax_tpu.engine.xla import NonNumericValues
    from bytewax_tpu.native import wa_encode as _probe

    if _probe([], {}, np.empty(0, np.int32), np.empty(0), np.empty(0)) is None:
        pytest.skip("native toolchain unavailable")

    offset_tz = timezone(timedelta(hours=2))
    st = WindowAccelSpec(
        "count",
        lambda x: x,
        ALIGN,
        timedelta(minutes=1),
        timedelta(minutes=1),
        timedelta(0),
    ).make_state()
    with pytest.raises(NonNumericValues):
        st.on_batch_items(
            [("a", datetime(2022, 1, 1, 2, 0, 1, tzinfo=offset_tz))]
        )


def test_itemized_tsvalue_flow_device_matches_host(monkeypatch):
    """End-to-end: a TsValue itemized stream through reduce_window
    rides the promotion on the device tier and matches the host tier
    exactly."""
    from bytewax_tpu import xla

    rng = np.random.RandomState(4)
    inp = [
        (
            f"k{rng.randint(0, 3)}",
            xla.TsValue(
                float(np.round(rng.randn(), 3)),
                ALIGN + timedelta(seconds=int(s)),
            ),
        )
        for s in range(300)
    ]
    windower = TumblingWindower(length=timedelta(minutes=1), align_to=ALIGN)

    def run(accel):
        monkeypatch.setenv("BYTEWAX_TPU_ACCEL", "1" if accel else "0")
        clock = EventClock(
            ts_getter=xla.column_ts,
            wait_for_system_duration=timedelta(0),
        )
        out = []
        flow = Dataflow("test_df")
        s = op.input("inp", flow, TestingSource(list(inp), batch_size=32))
        wo = w.reduce_window("sum", s, clock, windower, xla.SUM)
        op.output("out", wo.down, TestingSink(out))
        run_main(flow)
        return out

    got = run(True)
    want = run(False)
    gd = {(k, wid): v for k, (wid, v) in got}
    wd = {(k, wid): v for k, (wid, v) in want}
    assert gd.keys() == wd.keys()
    for kw in wd:
        # Device folds in f32; host in f64.
        assert gd[kw] == pytest.approx(wd[kw], abs=1e-4)
