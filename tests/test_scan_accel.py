"""Device lowering of ``stateful_map`` (segmented per-key scan):
host tier is the oracle; snapshots interchange between tiers."""

import os
import subprocess
import sys

import numpy as np
import pytest

import bytewax_tpu.operators as op
from bytewax_tpu import xla
from bytewax_tpu.dataflow import Dataflow
from bytewax_tpu.engine.flatten import flatten
from bytewax_tpu.engine.scan_accel import DeviceScanState, ScanAccelSpec
from bytewax_tpu.testing import TestingSink, TestingSource, run_main


def _host_oracle(items, threshold):
    """Run the marker mapper per item in Python (the host tier)."""
    states = {}
    out = []
    mapper = xla.zscore(threshold)
    for k, v in items:
        st, emit = mapper(states.get(k), v)
        states[k] = st
        out.append((k, emit))
    return states, out


def _flow(items, out, threshold, batch_size=7):
    flow = Dataflow("scan_accel")
    s = op.input("inp", flow, TestingSource(items, batch_size=batch_size))
    scored = op.stateful_map("zscore", s, xla.zscore(threshold))
    op.output("out", scored, TestingSink(out))
    return flow


def _assert_scored_equal(got, want, atol=1e-4):
    assert len(got) == len(want)
    # Per-key value sequences must match exactly and in order; z
    # within f32-vs-f64 tolerance; flags equal (test data keeps z
    # away from the threshold boundary).
    def per_key(rows):
        by = {}
        for k, (v, z, a) in rows:
            by.setdefault(k, []).append((v, z, a))
        return by

    g, w = per_key(got), per_key(want)
    assert g.keys() == w.keys()
    for k in w:
        assert len(g[k]) == len(w[k])
        for (gv, gz, ga), (wv, wz, wa) in zip(g[k], w[k]):
            assert gv == wv
            assert gz == pytest.approx(wz, abs=atol)
            assert ga == wa


def test_annotation_marks_scan_spec():
    flow = _flow([("a", 1.0)], [], 2.5)
    plan = flatten(flow)
    specs = [
        o.conf.get("_accel")
        for o in plan.ops
        if o.name == "stateful_batch"
    ]
    assert len(specs) == 1
    assert isinstance(specs[0], ScanAccelSpec)
    assert specs[0].kind.name == "zscore"
    assert specs[0].kind.threshold == 2.5


def test_unknown_scanmap_kind_stays_host_tier():
    # A user-defined ScanMap subclass with a kind the device tier
    # doesn't implement must lower to nothing and run as a plain
    # host mapper.
    class Running(xla.ScanMap):
        kind = "running_sum"

        def __call__(self, st, v):
            total = (st or 0.0) + v
            return total, total

    out = []
    flow = Dataflow("scan_custom")
    s = op.input("inp", flow, TestingSource([("a", 1.0), ("a", 2.0)]))
    s = op.stateful_map("m", s, Running())
    op.output("out", s, TestingSink(out))
    plan = flatten(flow)
    specs = [
        o.conf.get("_accel")
        for o in plan.ops
        if o.name == "stateful_batch"
    ]
    assert specs == [None]
    run_main(flow)
    assert out == [("a", 1.0), ("a", 3.0)]


def test_unmarked_mapper_not_annotated():
    flow = Dataflow("scan_plain")
    s = op.input("inp", flow, TestingSource([("a", 1.0)]))
    s = op.stateful_map("m", s, lambda st, v: ((st or 0) + v, v))
    op.output("out", s, TestingSink([]))
    plan = flatten(flow)
    specs = [
        o.conf.get("_accel")
        for o in plan.ops
        if o.name == "stateful_batch"
    ]
    assert specs == [None]


def test_device_matches_host_oracle(entry_point):
    rng = np.random.RandomState(7)
    items = [
        (f"k{rng.randint(0, 5)}", float(np.round(rng.randn(), 3)))
        for _ in range(400)
    ]
    # A couple of blatant outliers so both anomaly branches fire.
    items[200] = ("k0", 50.0)
    items[300] = ("k3", -40.0)
    _, want = _host_oracle(items, threshold=3.0)
    out = []
    entry_point(_flow(items, out, 3.0))
    _assert_scored_equal(out, want)


def test_single_item_batches_match_oracle():
    items = [("a", 1.0), ("b", 2.0), ("a", 3.0), ("a", 2.0), ("b", 9.0)]
    _, want = _host_oracle(items, threshold=2.0)
    out = []
    run_main(_flow(items, out, 2.0, batch_size=1))
    _assert_scored_equal(out, want)


def test_non_numeric_values_fall_back_to_host():
    # String values can't ride the device scan: the step must fall
    # back to the host tier, whose mapper then raises its own
    # arithmetic TypeError (same outcome as running unaccelerated).
    items = [("a", "x"), ("a", "x"), ("b", "y")]
    out = []
    flow = Dataflow("scan_fallback")
    s = op.input("inp", flow, TestingSource(items, batch_size=2))
    scored = op.stateful_map("zscore", s, xla.zscore(2.0))
    op.output("out", scored, TestingSink(out))
    with pytest.raises(TypeError):
        run_main(flow)


def test_mixed_malformed_rows_error_like_host():
    # Non-str key: host tier raises the step-qualified TypeError; the
    # device path must fall back and surface the same class of error.
    items = [(1, 2.0)]
    out = []
    with pytest.raises(TypeError, match="str"):
        run_main(_flow(items, out, 2.0))


def test_scan_state_snapshot_roundtrip():
    from bytewax_tpu.ops.scan import WelfordZScore

    st = DeviceScanState(WelfordZScore(2.0))
    touched, emit = st.update(
        np.array(["a", "a", "b"]), np.array([1.0, 2.0, 10.0])
    )
    assert sorted(touched) == ["a", "b"]
    snaps = dict(st.snapshots_for(["a", "b", "missing"]))
    assert snaps["missing"] is None
    count, mean, m2 = snaps["a"]
    assert count == 2
    assert mean == pytest.approx(1.5)
    assert m2 == pytest.approx(0.5)
    # Resume into a fresh state: continues identically.
    st2 = DeviceScanState(WelfordZScore(2.0))
    st2.load_many([(k, s) for k, s in snaps.items() if s is not None])
    _, emit2 = st2.update(np.array(["a"]), np.array([3.0]))
    mapper = xla.zscore(2.0)
    host_state = (2, 1.5, 0.5)
    _, (v, z, a) = mapper(host_state, 3.0)
    assert emit2.outs[0][0] == pytest.approx(z, abs=1e-5)
    assert bool(emit2.outs[1][0]) == a


def test_device_snapshot_resumes_on_host_tier(tmp_path, recovery_config):
    """Cross-tier recovery: snapshots written by the device scan must
    resume under the host tier (accel disabled) and vice versa."""
    from bytewax_tpu.testing import TestingSource as TS

    items = [("a", 1.0), ("a", 2.0), ("b", 5.0)]
    tail = [("a", 3.0), ("b", 6.0)]
    _, want = _host_oracle(items + tail, threshold=2.0)
    inp = items + [TS.ABORT()] + tail

    from datetime import timedelta

    out1 = []
    run_main(
        _flow(inp, out1, 2.0, batch_size=2),
        epoch_interval=timedelta(0),
        recovery_config=recovery_config,
    )
    assert len(out1) == len(items)

    out2 = []
    env_prev = os.environ.get("BYTEWAX_TPU_ACCEL")
    os.environ["BYTEWAX_TPU_ACCEL"] = "0"
    try:
        run_main(
            _flow(inp, out2, 2.0, batch_size=2),
            epoch_interval=timedelta(0),
            recovery_config=recovery_config,
        )
    finally:
        if env_prev is None:
            os.environ.pop("BYTEWAX_TPU_ACCEL", None)
        else:
            os.environ["BYTEWAX_TPU_ACCEL"] = env_prev
    _assert_scored_equal(out1 + out2, want, atol=1e-4)


def _oracle_for(mapper_factory, items):
    """Run any host mapper per item in Python (the host tier)."""
    states = {}
    out = []
    mapper = mapper_factory()
    for k, v in items:
        st, emit = mapper(states.get(k), v)
        states[k] = st
        out.append((k, emit))
    return states, out


def _rand_items(n=300, n_keys=4, seed=11):
    rng = np.random.RandomState(seed)
    return [
        (f"k{rng.randint(0, n_keys)}", float(np.round(rng.randn(), 3)))
        for _ in range(n)
    ]


def _run_kind_flow(items, mapper, batch_size=7):
    out = []
    flow = Dataflow("scan_kind")
    s = op.input("inp", flow, TestingSource(items, batch_size=batch_size))
    s = op.stateful_map("scan", s, mapper)
    op.output("out", s, TestingSink(out))
    plan = flatten(flow)
    specs = [
        o.conf.get("_accel")
        for o in plan.ops
        if o.name == "stateful_batch"
    ]
    assert isinstance(specs[0], ScanAccelSpec)
    run_main(flow)
    return out


def _assert_rows_close(got, want, atol=1e-4):
    assert len(got) == len(want)
    by_g, by_w = {}, {}
    for k, row in got:
        by_g.setdefault(k, []).append(row)
    for k, row in want:
        by_w.setdefault(k, []).append(row)
    assert by_g.keys() == by_w.keys()
    for k in by_w:
        for g_row, w_row in zip(by_g[k], by_w[k]):
            assert len(g_row) == len(w_row)
            for g_cell, w_cell in zip(g_row, w_row):
                if isinstance(w_cell, bool):
                    assert g_cell == w_cell
                else:
                    assert g_cell == pytest.approx(w_cell, abs=atol)


def test_ema_kind_matches_host_oracle():
    items = _rand_items()
    _, want = _oracle_for(lambda: xla.ema(0.3), items)
    got = _run_kind_flow(items, xla.ema(0.3))
    _assert_rows_close(got, want)


def test_extrema_kind_matches_host_oracle():
    items = _rand_items(seed=5)
    _, want = _oracle_for(xla.running_extrema, items)
    got = _run_kind_flow(items, xla.running_extrema())
    _assert_rows_close(got, want)


def test_ema_cross_tier_snapshot(recovery_config):
    """EMA snapshots written by the device tier resume on the host
    tier — the generic field-order snapshot contract."""
    from datetime import timedelta

    from bytewax_tpu.testing import TestingSource as TS

    items = [("a", 1.0), ("a", 2.0), ("b", 5.0)]
    tail = [("a", 3.0), ("b", 6.0)]
    _, want = _oracle_for(lambda: xla.ema(0.5), items + tail)
    inp = items + [TS.ABORT()] + tail

    def build(out):
        flow = Dataflow("scan_ema_rt")
        s = op.input("inp", flow, TestingSource(inp, batch_size=2))
        s = op.stateful_map("scan", s, xla.ema(0.5))
        op.output("out", s, TestingSink(out))
        return flow

    out1 = []
    run_main(
        build(out1),
        epoch_interval=timedelta(0),
        recovery_config=recovery_config,
    )
    out2 = []
    env_prev = os.environ.get("BYTEWAX_TPU_ACCEL")
    os.environ["BYTEWAX_TPU_ACCEL"] = "0"
    try:
        run_main(
            build(out2),
            epoch_interval=timedelta(0),
            recovery_config=recovery_config,
        )
    finally:
        if env_prev is None:
            os.environ.pop("BYTEWAX_TPU_ACCEL", None)
        else:
            os.environ["BYTEWAX_TPU_ACCEL"] = env_prev
    _assert_rows_close(out1 + out2, want)


def test_user_registered_kind_runs_on_device(recovery_config):
    """A ScanKind defined HERE — no engine changes — lowers through
    the generic kernel and round-trips snapshots cross-tier.

    The kind: per-key running sum with count, emitting
    ``(value, running_total)``.
    """
    import jax.numpy as jnp

    from bytewax_tpu.ops.scan import ScanKind

    class RunningSumKind(ScanKind):
        name = "running_sum"
        fields = {
            "count": (0, jnp.int32),
            "total": (0.0, jnp.float32),
        }

        def lift(self, values):
            n = values.shape[0]
            return jnp.ones((n,), dtype=jnp.int32), values

        def merge(self, a, b):
            return a[0] + b[0], a[1] + b[1]

        def emit(self, pre, post, values):
            return (post[1],)

    class RunningSumMap(xla.ScanMap):
        kind = "running_sum"

        def __call__(self, state, value):
            count, total = (0, 0.0) if state is None else state
            count += 1
            total += value
            return (count, total), (value, total)

        def device_kind(self):
            return RunningSumKind()

    items = [("a", 1.0), ("b", 10.0), ("a", 2.0), ("a", 3.0), ("b", 5.0)]
    _, want = _oracle_for(RunningSumMap, items)
    got = _run_kind_flow(items, RunningSumMap(), batch_size=2)
    _assert_rows_close(got, want)

    # Cross-tier: device-written snapshots resume under the host tier.
    from datetime import timedelta

    from bytewax_tpu.testing import TestingSource as TS

    tail = [("a", 4.0), ("b", 1.0)]
    _, want_all = _oracle_for(RunningSumMap, items + tail)
    inp = items + [TS.ABORT()] + tail

    def build(out):
        flow = Dataflow("scan_user_rt")
        s = op.input("inp", flow, TestingSource(inp, batch_size=2))
        s = op.stateful_map("scan", s, RunningSumMap())
        op.output("out", s, TestingSink(out))
        return flow

    out1 = []
    run_main(
        build(out1),
        epoch_interval=timedelta(0),
        recovery_config=recovery_config,
    )
    out2 = []
    env_prev = os.environ.get("BYTEWAX_TPU_ACCEL")
    os.environ["BYTEWAX_TPU_ACCEL"] = "0"
    try:
        run_main(
            build(out2),
            epoch_interval=timedelta(0),
            recovery_config=recovery_config,
        )
    finally:
        if env_prev is None:
            os.environ.pop("BYTEWAX_TPU_ACCEL", None)
        else:
            os.environ["BYTEWAX_TPU_ACCEL"] = env_prev
    _assert_rows_close(out1 + out2, want_all)


def test_zscore_generic_kernel_matches_specialized():
    """WelfordZScore's lift/merge/emit (the generic-kernel spelling)
    must agree with its specialized pivot-shifted kernel — they are
    two formulations of the same scan, and this pins them together so
    neither drifts."""
    import jax.numpy as jnp

    from bytewax_tpu.ops.scan import WelfordZScore, generic_scan_kernel

    rng = np.random.RandomState(9)
    n = 64
    slots = np.sort(rng.randint(0, 4, size=n)).astype(np.int32)
    vals = rng.randn(n).astype(np.float32)

    kind = WelfordZScore(2.0)
    def fresh_fields():
        # Both kernels donate their state argument: each needs its
        # own arrays.
        return {
            nm: jnp.full((8,), init, dtype=dt)
            for nm, (init, dt) in kind.fields.items()
        }

    fields_a = fresh_fields()
    fields_b = fresh_fields()

    (z_spec,), new_spec = kind.run(fields_a, jnp.asarray(slots), jnp.asarray(vals))
    generic = generic_scan_kernel(kind)
    (z_gen,), new_gen = generic(fields_b, jnp.asarray(slots), jnp.asarray(vals))

    np.testing.assert_allclose(
        np.asarray(z_spec), np.asarray(z_gen), atol=1e-4
    )
    for nm in kind.fields:
        np.testing.assert_allclose(
            np.asarray(new_spec[nm])[:4],
            np.asarray(new_gen[nm])[:4],
            atol=1e-3,
        )


def test_ema_tiny_alpha_stays_finite():
    """alpha below f32's rounding of 1-alpha must not collapse the
    debias factor (naive (1-alpha)^n rounds to 1 and divides by ~0);
    the expm1/log1p spelling keeps device ≈ host."""
    alpha = 1e-8
    items = [("a", float(v)) for v in [3.0, 5.0, 4.0, 6.0]]
    _, want = _oracle_for(lambda: xla.ema(alpha), items)
    got = _run_kind_flow(items, xla.ema(alpha), batch_size=2)
    _assert_rows_close(got, want, atol=1e-3)


def test_count_stays_exact_past_fp24():
    """The Welford count rides int32 end-to-end: a key whose lifetime
    count exceeds 2^24 keeps counting exactly (an fp32 count would
    freeze at 16,777,216: n + 1 == n)."""
    from bytewax_tpu.ops.scan import WelfordZScore

    big = 1 << 24
    st = DeviceScanState(WelfordZScore(3.0))
    st.load_many([("a", (big, 0.0, 1000.0))])
    st.update(np.array(["a", "a"]), np.array([1.0, -1.0]))
    (count, _mean, _m2) = dict(st.snapshots_for(["a"]))["a"]
    assert count == big + 2


def test_welford_merge_matches_sequential():
    import jax.numpy as jnp

    from bytewax_tpu.ops.scan import welford_merge

    rng = np.random.RandomState(3)
    xs = rng.randn(100)
    # Sequential host fold.
    count, mean, m2 = 0, 0.0, 0.0
    for v in xs:
        count += 1
        d = v - mean
        mean += d / count
        m2 += d * (v - mean)
    # Pairwise device merge of the two halves.
    def summarize(arr):
        c, m, s = 0, 0.0, 0.0
        for v in arr:
            c += 1
            d = v - m
            m += d / c
            s += d * (v - m)
        return (
            jnp.asarray(c, jnp.int32),
            jnp.asarray(m, jnp.float32),
            jnp.asarray(s, jnp.float32),
        )

    n, me, s2 = welford_merge(summarize(xs[:50]), summarize(xs[50:]))
    assert int(n) == count
    assert float(me) == pytest.approx(mean, abs=1e-5)
    assert float(s2) == pytest.approx(m2, rel=1e-4)


def test_example_anomaly_detector_runs_device_tier(tmp_path):
    """The BASELINE config flow must actually engage the scan accel:
    run it in-process and assert the plan annotation plus output."""
    from bytewax_tpu.connectors.demo import RandomMetricSource
    from datetime import timedelta

    flow = Dataflow("anomaly_device")
    s = op.input(
        "inp",
        flow,
        RandomMetricSource(
            "metric", interval=timedelta(0), count=50, seed=1
        ),
    )
    scored = op.stateful_map("zscore", s, xla.zscore(2.5))
    out = []
    op.output("out", scored, TestingSink(out))
    plan = flatten(flow)
    assert any(
        isinstance(o.conf.get("_accel"), ScanAccelSpec)
        for o in plan.ops
        if o.name == "stateful_batch"
    )
    run_main(flow)
    assert len(out) == 50
    assert all(k == "metric" for k, _ in out)


def test_jax_stateful_map_matches_host_oracle():
    """The traceable-UDF tier: an arbitrary (non-associative) jax
    mapper — capped running total with a decay — runs through the
    compiled lax.scan kernel and matches the host tier per row."""
    import jax.numpy as jnp

    def capped_decay(state, v):
        total, n = state
        total = jnp.minimum(total * 0.9 + v, 50.0)
        n = n + 1
        return (total, n), (total, n)

    items = _rand_items(n=250, n_keys=5, seed=21)
    _, want = _oracle_for(
        lambda: xla.jax_stateful_map(capped_decay, (0.0, 0)), items
    )
    got = _run_kind_flow(
        items, xla.jax_stateful_map(capped_decay, (0.0, 0)), batch_size=16
    )
    _assert_rows_close(got, want, atol=1e-4)
    # The int state field stays an exact int through the device tier.
    assert all(isinstance(row[-1], int) for _k, row in got)


def test_jax_stateful_map_cross_tier_snapshot(recovery_config):
    from datetime import timedelta

    from bytewax_tpu.testing import TestingSource as TS

    def runsum(state, v):
        (total,) = state
        total = total + v
        return (total,), (total,)

    def make():
        return xla.jax_stateful_map(runsum, (0.0,))

    items = [("a", 1.0), ("b", 10.0), ("a", 2.0)]
    tail = [("a", 3.0), ("b", 5.0)]
    _, want = _oracle_for(make, items + tail)
    inp = items + [TS.ABORT()] + tail

    def build(out):
        flow = Dataflow("scan_udf_rt")
        s = op.input("inp", flow, TestingSource(inp, batch_size=2))
        s = op.stateful_map("scan", s, make())
        op.output("out", s, TestingSink(out))
        return flow

    out1 = []
    run_main(
        build(out1),
        epoch_interval=timedelta(0),
        recovery_config=recovery_config,
    )
    out2 = []
    env_prev = os.environ.get("BYTEWAX_TPU_ACCEL")
    os.environ["BYTEWAX_TPU_ACCEL"] = "0"
    try:
        run_main(
            build(out2),
            epoch_interval=timedelta(0),
            recovery_config=recovery_config,
        )
    finally:
        if env_prev is None:
            os.environ.pop("BYTEWAX_TPU_ACCEL", None)
        else:
            os.environ["BYTEWAX_TPU_ACCEL"] = env_prev
    _assert_rows_close(out1 + out2, want)


def test_jax_stateful_map_bool_state_cross_tier_snapshot(
    recovery_config,
):
    """Bool state fields must snapshot as exact Python bools on the
    device tier (ScanKind.snapshot_of's jnp.bool_ branch): a latch
    armed before the abort must resume armed on the HOST tier, and
    the stored snapshot itself must carry a bool, not a 1.0 float."""
    import pickle
    from datetime import timedelta

    import jax.numpy as jnp

    from bytewax_tpu.engine.recovery_store import RecoveryStore
    from bytewax_tpu.testing import TestingSource as TS

    def latch(state, v):
        (armed,) = state
        armed = jnp.logical_or(armed, v > 5.0)
        return (armed,), (armed,)

    def make():
        return xla.jax_stateful_map(latch, (False,))

    items = [("a", 1.0), ("a", 9.0), ("b", 2.0)]
    tail = [("a", 0.5), ("b", 1.0)]
    _, want = _oracle_for(make, items + tail)
    inp = items + [TS.ABORT()] + tail

    def build(out):
        flow = Dataflow("scan_bool_rt")
        s = op.input("inp", flow, TestingSource(inp, batch_size=1))
        s = op.stateful_map("scan", s, make())
        op.output("out", s, TestingSink(out))
        return flow

    out1 = []
    run_main(
        build(out1),
        epoch_interval=timedelta(0),
        recovery_config=recovery_config,
    )
    # The device-tier snapshot rows hold exact Python bools.
    store = RecoveryStore(recovery_config.db_dir)
    try:
        snaps = {
            key: pickle.loads(ser)
            for sid, key, ser in store.iter_snaps(10**6)
            if "stateful_batch" in sid
        }
    finally:
        store.close()
    assert snaps, "expected scan-state snapshots in the store"
    for state in snaps.values():
        assert isinstance(state[0], bool), state
    assert snaps["a"] == (True,)
    # And the host tier resumes from them with identical semantics.
    out2 = []
    env_prev = os.environ.get("BYTEWAX_TPU_ACCEL")
    os.environ["BYTEWAX_TPU_ACCEL"] = "0"
    try:
        run_main(
            build(out2),
            epoch_interval=timedelta(0),
            recovery_config=recovery_config,
        )
    finally:
        if env_prev is None:
            os.environ.pop("BYTEWAX_TPU_ACCEL", None)
        else:
            os.environ["BYTEWAX_TPU_ACCEL"] = env_prev
    got = out1 + out2
    _assert_rows_close(got, want)
    # Host-tier emissions after the resume are exact bools too (the
    # scalar-path mirror in _JaxStatefulMap.__call__).
    assert all(isinstance(row[1], bool) for _k, row in out2)


def test_jax_stateful_map_rejects_bad_fns_at_construction():
    import jax.numpy as jnp

    # Python control flow on traced state: rejected up front.
    def branchy(state, v):
        (total,) = state
        if total > 50:  # concretizes a tracer
            total = 0.0
        return (total + v,), (total,)

    with pytest.raises(TypeError, match="traceable"):
        xla.jax_stateful_map(branchy, (0.0,))

    # Wrong state arity: rejected up front.
    def shrinker(state, v):
        total, _n = state
        return (total + v,), (total,)

    with pytest.raises(TypeError, match="state fields"):
        xla.jax_stateful_map(shrinker, (0.0, 0))

    # A valid fn still constructs.
    def ok(state, v):
        (total,) = state
        return (jnp.minimum(total + v, 9.0),), (total,)

    assert xla.jax_stateful_map(ok, (0.0,)) is not None
