"""Recovery/resume semantics tests (model:
``/root/reference/pytests/test_recovery.py`` — same scenarios, asserting
identical replay sets)."""

import os
import shutil
from datetime import timedelta

import pytest

import bytewax_tpu.operators as op
from bytewax_tpu.dataflow import Dataflow
from bytewax_tpu.recovery import (
    InconsistentPartitionsError,
    MissingPartitionsError,
    NoPartitionsError,
    RecoveryConfig,
    init_db_dir,
)
from bytewax_tpu.testing import TestingSink, TestingSource, cluster_main, run_main

ZERO_TD = timedelta(seconds=0)
FIVE_TD = timedelta(seconds=5)


def test_abort_no_snapshots(recovery_config):
    inp = [0, 1, 2, TestingSource.ABORT(), 3, 4]
    out = []

    flow = Dataflow("test_df")
    s = op.input("inp", flow, TestingSource(inp))
    op.output("out", s, TestingSink(out))

    # Epoch interval of 5s means no snapshot before the abort.
    run_main(flow, epoch_interval=FIVE_TD, recovery_config=recovery_config)
    assert out == [0, 1, 2]

    # So resume replays all input.
    out.clear()
    run_main(flow, epoch_interval=FIVE_TD, recovery_config=recovery_config)
    assert out == [0, 1, 2, 3, 4]


def test_abort_with_snapshots(recovery_config):
    inp = [0, 1, 2, TestingSource.ABORT(), 3, 4]
    out = []

    flow = Dataflow("test_df")
    s = op.input("inp", flow, TestingSource(inp))
    op.output("out", s, TestingSink(out))

    # Epoch interval of 0 means a snapshot after each item.
    run_main(flow, epoch_interval=ZERO_TD, recovery_config=recovery_config)
    assert out == [0, 1, 2]

    # Resume as if it was an EOF.
    out.clear()
    run_main(flow, epoch_interval=ZERO_TD, recovery_config=recovery_config)
    assert out == [3, 4]


def test_continuation(recovery_config):
    inp = [0, 1, 2, TestingSource.EOF(), 3, 4]
    out = []

    flow = Dataflow("test_df")
    s = op.input("inp", flow, TestingSource(inp))
    op.output("out", s, TestingSink(out))

    run_main(flow, epoch_interval=FIVE_TD, recovery_config=recovery_config)
    assert out == [0, 1, 2]

    out.clear()
    run_main(flow, epoch_interval=FIVE_TD, recovery_config=recovery_config)
    assert out == [3, 4]

    out.clear()
    run_main(flow, epoch_interval=FIVE_TD, recovery_config=recovery_config)
    assert out == []

    out.clear()
    run_main(flow, epoch_interval=FIVE_TD, recovery_config=recovery_config)
    assert out == []


def test_continuation_with_delayed_backup(tmp_path):
    init_db_dir(tmp_path, 1)
    recovery_config = RecoveryConfig(str(tmp_path), backup_interval=FIVE_TD * 2)

    inp = [
        0,
        TestingSource.EOF(),
        1,
        TestingSource.EOF(),
        2,
        TestingSource.EOF(),
        3,
        TestingSource.EOF(),
        4,
    ]
    out = []

    flow = Dataflow("test_df")
    s = op.input("inp", flow, TestingSource(inp))
    op.output("out", s, TestingSink(out))

    for expect in ([0], [1], [2], [3], [4], []):
        out.clear()
        run_main(flow, epoch_interval=FIVE_TD, recovery_config=recovery_config)
        assert out == expect


def keep_max(max_val, new_val):
    if max_val is None:
        max_val = 0
    max_val = max(max_val, new_val)
    return (max_val, max_val)


def build_keep_max_dataflow(inp, out):
    flow = Dataflow("test_df")
    s = op.input("inp", flow, TestingSource(inp))
    s = op.stateful_map("max", s, keep_max)
    op.output("out", s, TestingSink(out))
    return flow


def test_stateful_continuation(recovery_config):
    inp = [
        ("a", 4),
        ("b", 4),
        TestingSource.EOF(),
        ("a", 1),
        ("b", 5),
    ]
    out = []
    flow = build_keep_max_dataflow(inp, out)

    run_main(flow, epoch_interval=ZERO_TD, recovery_config=recovery_config)
    assert out == [("a", 4), ("b", 4)]

    # State (max so far) must survive the continuation.
    out.clear()
    run_main(flow, epoch_interval=ZERO_TD, recovery_config=recovery_config)
    assert out == [("a", 4), ("b", 5)]


def test_rescale(tmp_path, monkeypatch):
    # Rescale-on-resume is opt-in: with BYTEWAX_TPU_RESCALE=1 the
    # keyed state is re-sharded to the new worker count at run
    # startup (grow AND shrink), state intact across every resize.
    monkeypatch.setenv("BYTEWAX_TPU_RESCALE", "1")
    init_db_dir(tmp_path, 3)
    recovery_config = RecoveryConfig(str(tmp_path))

    inp = [
        ("a", 4),
        ("b", 4),
        TestingSource.EOF(),
        ("a", 1),
        ("b", 5),
        TestingSource.EOF(),
        ("a", 8),
        ("b", 1),
    ]
    out = []

    flow = build_keep_max_dataflow(inp, out)

    def entry_point(worker_count_per_proc):
        cluster_main(
            flow,
            addresses=[],
            proc_id=0,
            epoch_interval=ZERO_TD,
            recovery_config=recovery_config,
            worker_count_per_proc=worker_count_per_proc,
        )

    # 2 continuations with different worker counts each time.
    entry_point(3)
    assert out == [("a", 4), ("b", 4)]

    out.clear()
    entry_point(5)
    assert out == [("a", 4), ("b", 5)]

    out.clear()
    entry_point(1)
    assert out == [("a", 8), ("b", 5)]


def test_rescale_refused_without_flag(tmp_path, monkeypatch):
    # Resuming a store written by N workers at M != N without the
    # rescale opt-in must raise the typed mismatch error (naming the
    # stored and actual counts and how to enable rescale) instead of
    # silently routing snaps rows with a stale modulus.
    from bytewax_tpu.recovery import WorkerCountMismatchError

    monkeypatch.delenv("BYTEWAX_TPU_RESCALE", raising=False)
    init_db_dir(tmp_path, 2)
    recovery_config = RecoveryConfig(str(tmp_path))
    inp = [("a", 4), ("b", 7), TestingSource.EOF(), ("a", 9)]
    out = []
    flow = build_keep_max_dataflow(inp, out)

    def entry_point(worker_count_per_proc):
        cluster_main(
            flow,
            addresses=[],
            proc_id=0,
            epoch_interval=ZERO_TD,
            recovery_config=recovery_config,
            worker_count_per_proc=worker_count_per_proc,
        )

    entry_point(3)
    assert out == [("a", 4), ("b", 7)]
    with pytest.raises(
        WorkerCountMismatchError,
        match=r"3 worker\(s\).*has 2.*BYTEWAX_TPU_RESCALE=1",
    ) as exc_info:
        entry_point(2)
    assert exc_info.value.stored_counts == (3,)
    assert exc_info.value.actual_count == 2
    # Nothing was consumed or emitted by the refused execution; the
    # same-count resume still works.
    out.clear()
    entry_point(3)
    assert out == [("a", 9)]


def test_no_parts(tmp_path):
    # Don't init_db_dir.
    recovery_config = RecoveryConfig(str(tmp_path))

    inp = []
    out = []
    flow = build_keep_max_dataflow(inp, out)

    with pytest.raises(NoPartitionsError):
        run_main(flow, epoch_interval=ZERO_TD, recovery_config=recovery_config)


def test_missing_parts(tmp_path):
    init_db_dir(tmp_path, 3)
    recovery_config = RecoveryConfig(str(tmp_path))

    os.remove(tmp_path / "part-0.sqlite3")

    inp = []
    out = []
    flow = build_keep_max_dataflow(inp, out)

    with pytest.raises(MissingPartitionsError):
        run_main(flow, epoch_interval=ZERO_TD, recovery_config=recovery_config)


def test_inconsistent_parts(tmp_path):
    part_count = 3
    init_db_dir(tmp_path, part_count)
    recovery_config = RecoveryConfig(str(tmp_path), backup_interval=ZERO_TD)

    for i in range(part_count):
        shutil.copy(tmp_path / f"part-{i}.sqlite3", tmp_path / f"part-{i}.run0")

    inp = [
        ("a", 4),
        ("b", 4),
        TestingSource.ABORT(),
        ("a", 1),
        ("b", 5),
    ]
    out = []
    flow = build_keep_max_dataflow(inp, out)

    run_main(flow, epoch_interval=ZERO_TD, recovery_config=recovery_config)
    assert out == [("a", 4), ("b", 4)]

    # Overwrite partition 0 with its initial (pre-run) version.  With
    # backup interval 0 the other partitions have already GC'd the
    # state needed to resume that far back.
    out.clear()
    shutil.copy(tmp_path / "part-0.run0", tmp_path / "part-0.sqlite3")
    with pytest.raises(InconsistentPartitionsError):
        run_main(flow, epoch_interval=ZERO_TD, recovery_config=recovery_config)


def test_fold_final_discard_not_resurrected(recovery_config):
    # fold_final emits at EOF and discards its state; the discard must
    # be durable so the key is not resurrected on the next execution.
    inp = [
        ("a", 1),
        ("a", 2),
        TestingSource.EOF(),
        ("b", 10),
    ]
    out = []
    flow = Dataflow("test_df")
    s = op.input("inp", flow, TestingSource(inp))
    s = op.fold_final("sum", s, int, lambda acc, x: acc + x)
    op.output("out", s, TestingSink(out))

    run_main(flow, epoch_interval=ZERO_TD, recovery_config=recovery_config)
    assert sorted(out) == [("a", 3)]

    out.clear()
    run_main(flow, epoch_interval=ZERO_TD, recovery_config=recovery_config)
    assert sorted(out) == [("b", 10)]


def test_fold_final_resume_mid_stream_keeps_state(recovery_config):
    # An ABORT mid-stream must preserve partial fold state so the
    # final result is identical to an uninterrupted run.
    inp = [
        ("a", 1),
        TestingSource.ABORT(),
        ("a", 2),
    ]
    out = []
    flow = Dataflow("test_df")
    s = op.input("inp", flow, TestingSource(inp))
    s = op.fold_final("sum", s, int, lambda acc, x: acc + x)
    op.output("out", s, TestingSink(out))

    run_main(flow, epoch_interval=ZERO_TD, recovery_config=recovery_config)
    assert out == []

    run_main(flow, epoch_interval=ZERO_TD, recovery_config=recovery_config)
    assert out == [("a", 3)]


def test_resume_from_inconsistent_commit_watermark(tmp_path):
    # Store-level coverage of the resume_from() inconsistency check:
    # a partition whose GC watermark reached (or passed) the computed
    # resume epoch came from a newer backup than its siblings — resume
    # must refuse with a message naming the partition, the watermark,
    # and the resume epoch.
    import sqlite3

    from bytewax_tpu.engine.recovery_store import RecoveryStore

    init_db_dir(tmp_path, 2)
    store = RecoveryStore(tmp_path)
    store.write_ex_started(0, 1, 1)
    store.write_epoch(0, 1, 1, [], None)
    store.write_epoch(0, 1, 2, [], None)
    assert store.resume_from().resume_epoch == 3

    # Poison partition 1 with a commit watermark at the resume epoch
    # (simulating siblings restored from older backups).
    con = sqlite3.connect(tmp_path / "part-1.sqlite3")
    con.execute("INSERT OR REPLACE INTO commits (epoch) VALUES (3)")
    con.commit()
    con.close()
    with pytest.raises(
        InconsistentPartitionsError,
        match=(
            r"partition 1 already garbage-collected state up to "
            r"epoch 3.*resume epoch is 3.*inconsistent backups"
        ),
    ):
        store.resume_from()
    store.close()


def test_resume_from_commit_watermark_boundary_ok(tmp_path):
    # The boundary case must NOT raise: a watermark strictly below the
    # resume epoch is the normal delayed-GC state.
    import sqlite3

    from bytewax_tpu.engine.recovery_store import RecoveryStore

    init_db_dir(tmp_path, 2)
    store = RecoveryStore(tmp_path)
    store.write_ex_started(0, 1, 1)
    store.write_epoch(0, 1, 1, [], None)
    store.write_epoch(0, 1, 2, [], None)
    con = sqlite3.connect(tmp_path / "part-0.sqlite3")
    con.execute("INSERT OR REPLACE INTO commits (epoch) VALUES (2)")
    con.commit()
    con.close()
    resume = store.resume_from()
    assert (resume.ex_num, resume.resume_epoch) == (1, 3)
    store.close()


def test_resume_from_lost_exs_row_does_not_constrain(tmp_path):
    # A worker of the last execution whose exs row was lost (stale
    # partition restored from backup) must not drag the resume epoch
    # down to its start epoch; only surviving exs rows constrain the
    # minimum, and the commit check still guards real inconsistency.
    import sqlite3

    from bytewax_tpu.engine.recovery_store import RecoveryStore

    init_db_dir(tmp_path, 2)
    store = RecoveryStore(tmp_path)
    store.write_ex_started(0, 2, 1)  # workers 0 and 1
    store.write_epoch(0, 2, 1, [], None)
    store.write_epoch(0, 2, 5, [], None)
    assert store.resume_from().resume_epoch == 6

    # Drop worker 1's exs row (it lives in partition 1 % 2).
    con = sqlite3.connect(tmp_path / "part-1.sqlite3")
    con.execute("DELETE FROM exs WHERE worker_index = 1")
    con.commit()
    con.close()
    resume = store.resume_from()
    # Worker 0's frontier still decides; worker 1's orphaned front
    # row is ignored rather than treated as a brand-new worker at the
    # start epoch.
    assert (resume.ex_num, resume.resume_epoch) == (1, 6)
    store.close()


def test_inconsistent_parts_error_wording():
    # The class docstring is user-facing guidance (it names the
    # backup_interval knob); pin the wording the engine relies on.
    assert issubclass(InconsistentPartitionsError, ValueError)
    assert "backup_interval" in (InconsistentPartitionsError.__doc__ or "")


def test_iter_snaps_paginates_latest_per_key(tmp_path):
    # Keyset-paginated snapshot reads: latest epoch wins, discard
    # markers drop the key, step filter applies — identical results
    # at any page size (reference pages at 1000: src/recovery.rs:817).
    import pickle

    from bytewax_tpu.engine.recovery_store import RecoveryStore

    init_db_dir(tmp_path, 3)
    store = RecoveryStore(tmp_path)
    store.write_ex_started(0, 1, 1)
    snaps1 = [("df.a", f"k{i:03d}", pickle.dumps(i)) for i in range(100)]
    snaps1 += [("df.b", "x", pickle.dumps("old"))]
    store.write_epoch(0, 1, 1, snaps1, None)
    snaps2 = [("df.a", f"k{i:03d}", pickle.dumps(i * 10)) for i in range(0, 100, 2)]
    snaps2 += [("df.a", "k001", None)]  # discard marker
    snaps2 += [("df.b", "x", pickle.dumps("new"))]
    store.write_epoch(0, 1, 2, snaps2, None)

    def collect(**kw):
        return {
            (s, k): pickle.loads(b) for s, k, b in store.iter_snaps(3, **kw)
        }

    expect = {("df.a", f"k{i:03d}"): (i * 10 if i % 2 == 0 else i) for i in range(100)}
    del expect[("df.a", "k001")]
    expect[("df.b", "x")] = "new"
    assert collect(page_size=7) == expect
    assert collect(page_size=100000) == expect
    only_a = collect(page_size=7, step_ids=["df.a"])
    assert set(s for s, _k in only_a) == {"df.a"}
    # Reads strictly before an epoch exclude that epoch's writes.
    before2 = {
        (s, k): pickle.loads(b)
        for s, k, b in store.iter_snaps(2, page_size=7)
    }
    assert before2[("df.b", "x")] == "old"
    assert before2[("df.a", "k001")] == 1


def test_resume_memory_bounded_by_paging(tmp_path, monkeypatch):
    # A synthetic large keyed state resumes through the engine in
    # store pages: the peak python allocation during resume must stay
    # far below the cost of materializing every blob in one dict
    # (~100 MB for this shape), and the monolithic load_snaps must
    # not be called at all.
    import pickle
    import tracemalloc

    from bytewax_tpu.engine.recovery_store import RecoveryStore
    from bytewax_tpu.xla import SUM

    n = 150_000
    init_db_dir(tmp_path, 2)
    store = RecoveryStore(tmp_path)
    store.write_ex_started(0, 1, 1)
    step = "test_df.sum.fold_final.stateful.stateful_batch"
    store.write_epoch(
        0,
        1,
        1,
        [(step, f"key{i:07d}", pickle.dumps(float(i))) for i in range(n)],
        None,
    )
    store.write_epoch(0, 1, 2, [], None)
    store.close()

    monkeypatch.setattr(
        RecoveryStore,
        "load_snaps",
        lambda *a, **k: pytest.fail("resume must stream, not load_snaps"),
    )
    rc = RecoveryConfig(str(tmp_path))
    out = []
    flow = Dataflow("test_df")
    s = op.input("inp", flow, TestingSource([("key0000000", 1.0)]))
    r = op.reduce_final("sum", s, SUM)
    keep = ("key0000000", "key0149999")
    r = op.filter("keep", r, lambda kv: kv[0] in keep)
    op.output("out", r, TestingSink(out))
    tracemalloc.start()
    run_main(flow, recovery_config=rc)
    _cur, peak = tracemalloc.get_traced_memory()
    tracemalloc.stop()
    assert dict(out) == {"key0000000": 1.0, "key0149999": 149999.0}
    # Live resumed state (slot tables + key maps) is ~25 MB here; the
    # all-blobs dict alone would add >40 MB on top.
    assert peak < 45 * 1024 * 1024, f"resume peaked at {peak/1e6:.0f} MB"
