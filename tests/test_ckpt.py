"""Asynchronous incremental checkpoints (docs/recovery.md
"Asynchronous incremental checkpoints"): delta snapshots sealed at
the epoch-close drain point, committed on the committer lane off the
close critical path.

The synchronous whole-state checkpointer is the oracle: with the
knobs on, every completed run must emit identical output, a clean
exit must resume with zero replayed epochs, and a crash anywhere in
the seal→commit window must resume exactly-once.  Faults are
injected ONLY through the engine's own injector (the pinned
``snapshot_seal`` site plus the store's ``snapshot.write`` /
``snapshot.commit`` sites, which now fire on the committer lane) —
no monkeypatching of engine internals.
"""

import pickle
import sqlite3
import subprocess
import sys
from datetime import timedelta
from pathlib import Path

import pytest

import bytewax_tpu.operators as op
from bytewax_tpu import xla
from bytewax_tpu.dataflow import Dataflow
from bytewax_tpu.engine import faults, flight
from bytewax_tpu.engine.driver import derive_rescale_hint
from bytewax_tpu.engine.recovery_store import (
    RecoveryStore,
    route_of,
)
from bytewax_tpu.recovery import RecoveryConfig, init_db_dir
from bytewax_tpu.testing import TestingSink, TestingSource, run_main

ZERO_TD = timedelta(seconds=0)
RETAIN_TD = timedelta(hours=1)  # delay GC: retain every snaps row


@pytest.fixture(autouse=True)
def _fresh_fault_plan():
    faults.reset()
    yield
    faults.reset()


def _ckpt_env(monkeypatch, async_=True, delta=True, compact=None):
    if async_:
        monkeypatch.setenv("BYTEWAX_TPU_CKPT_ASYNC", "1")
    else:
        monkeypatch.delenv("BYTEWAX_TPU_CKPT_ASYNC", raising=False)
    if delta:
        monkeypatch.setenv("BYTEWAX_TPU_CKPT_DELTA", "1")
    else:
        monkeypatch.delenv("BYTEWAX_TPU_CKPT_DELTA", raising=False)
    if compact is not None:
        monkeypatch.setenv(
            "BYTEWAX_TPU_CKPT_COMPACT_EVERY", str(compact)
        )
    else:
        monkeypatch.delenv(
            "BYTEWAX_TPU_CKPT_COMPACT_EVERY", raising=False
        )


def _file_flow(inp, out_path):
    from bytewax_tpu.connectors.files import FileSink

    flow = Dataflow("ckpt_df")
    s = op.input("inp", flow, TestingSource(inp))
    s = op.stateful_map(
        "sum", s, lambda st, v: ((st or 0) + v, (st or 0) + v)
    )
    s = op.map("fmt", s, lambda kv: (kv[0], f"{kv[0]}={kv[1]}"))
    op.output("out", s, FileSink(out_path))
    return flow


def _running_sum_oracle(inp):
    sums, want = {}, []
    for k, v in inp:
        sums[k] = sums.get(k, 0) + v
        want.append(f"{k}={sums[k]}")
    return sorted(want)


def _mk_db(tmp_path, name):
    db = tmp_path / name
    db.mkdir()
    init_db_dir(db, 1)
    return db


def _snaps_rows(db):
    rows = []
    for part in sorted(Path(db).glob("part-*.sqlite3")):
        con = sqlite3.connect(part)
        try:
            rows += con.execute(
                "SELECT step_id, state_key, epoch, route, ser_change"
                " FROM snaps"
            ).fetchall()
        finally:
            con.close()
    return rows


# -- async + delta vs the synchronous oracle ---------------------------


def test_async_delta_matches_sync_oracle_and_drains_clean(
    entry_point, tmp_path, monkeypatch
):
    """With both knobs on, a fault-free run emits exactly the
    synchronous engine's output, the run-ending close fences the
    committer lane (clean exit = fully durable), and a resume
    replays zero epochs."""
    _ckpt_env(monkeypatch, async_=True, delta=True, compact=3)
    inp = [(f"k{i % 3}", i) for i in range(12)]
    out_path = tmp_path / "out.txt"
    db = _mk_db(tmp_path, "db")
    entry_point(
        _file_flow(inp, str(out_path)),
        epoch_interval=ZERO_TD,
        recovery_config=RecoveryConfig(str(db)),
    )
    assert sorted(out_path.read_text().split()) == _running_sum_oracle(
        inp
    )
    # Durability bookkeeping landed at lag 0: the final fence
    # committed the last sealed epoch before teardown.
    assert flight.RECORDER.counters.get("snapshot_lag_epochs") == 0
    from bytewax_tpu._metrics import snapshot_lag_epochs

    assert (
        next(iter(snapshot_lag_epochs.collect())).samples[0].value == 0
    )
    # Clean exit replays ZERO epochs: resume appends nothing.
    entry_point(
        _file_flow(inp, str(out_path)),
        epoch_interval=ZERO_TD,
        recovery_config=RecoveryConfig(str(db)),
    )
    assert sorted(out_path.read_text().split()) == _running_sum_oracle(
        inp
    )


# -- crash in the seal→commit window, all three entry points -----------


def test_seal_crash_replays_exactly_once(
    entry_point, tmp_path, monkeypatch
):
    """An injected crash at the pinned ``snapshot_seal`` site — the
    delta is sealed in memory, nothing durable has happened, and the
    PREVIOUS epoch's async commit may still be in flight — unwinds
    restartable.  Resume replays at most the sealed epoch plus the
    one unfenced commit, and the sink truncates to its snapshotted
    offset, so the final output is exactly-once vs the host oracle."""
    _ckpt_env(monkeypatch, async_=True, delta=True)
    monkeypatch.setenv("BYTEWAX_TPU_FAULTS", "snapshot_seal:crash:3:x1")
    monkeypatch.setenv("BYTEWAX_TPU_MAX_RESTARTS", "2")
    monkeypatch.setenv("BYTEWAX_TPU_RESTART_BACKOFF_S", "0.05")
    inp = [(f"k{i % 3}", i) for i in range(12)]
    out_path = tmp_path / "out.txt"
    db = _mk_db(tmp_path, "db")
    restarts_before = flight.RECORDER.counters.get(
        "worker_restart_count", 0
    )
    entry_point(
        _file_flow(inp, str(out_path)),
        epoch_interval=ZERO_TD,
        recovery_config=RecoveryConfig(str(db)),
    )
    assert (
        flight.RECORDER.counters.get("worker_restart_count", 0)
        == restarts_before + 1
    )
    assert sorted(out_path.read_text().split()) == _running_sum_oracle(
        inp
    )


def test_committer_lane_crash_replays_exactly_once(
    entry_point, tmp_path, monkeypatch
):
    """With async on, the store's ``snapshot.commit`` site fires on
    the committer lane's worker thread; the injected crash surfaces
    at the next fence, the write transaction rolls back whole, and
    the supervised resume replays that epoch exactly-once."""
    _ckpt_env(monkeypatch, async_=True, delta=True)
    monkeypatch.setenv(
        "BYTEWAX_TPU_FAULTS", "snapshot.commit:crash:3:x1"
    )
    monkeypatch.setenv("BYTEWAX_TPU_MAX_RESTARTS", "2")
    monkeypatch.setenv("BYTEWAX_TPU_RESTART_BACKOFF_S", "0.05")
    inp = [(f"k{i % 3}", i) for i in range(12)]
    out_path = tmp_path / "out.txt"
    db = _mk_db(tmp_path, "db")
    entry_point(
        _file_flow(inp, str(out_path)),
        epoch_interval=ZERO_TD,
        recovery_config=RecoveryConfig(str(db)),
    )
    assert sorted(out_path.read_text().split()) == _running_sum_oracle(
        inp
    )


def test_random_soak_snapshot_seal_site(monkeypatch):
    """The new site participates in the seeded random soak and the
    ``BYTEWAX_TPU_FAULTS_SITES`` restriction, like every other."""
    monkeypatch.setenv("BYTEWAX_TPU_FAULTS", "random")
    monkeypatch.setenv("BYTEWAX_TPU_FAULTS_SITES", "snapshot_seal")
    monkeypatch.setenv("BYTEWAX_TPU_FAULTS_KINDS", "crash")
    monkeypatch.setenv("BYTEWAX_TPU_FAULTS_RATE", "1.0")
    monkeypatch.setenv("BYTEWAX_TPU_FAULTS_MIN_GAP_S", "0")
    faults.reset()
    faults.configure(0)
    # Filtered-out sites never fire...
    assert faults.fire("comm.send") is None
    assert faults.fire("snapshot.commit") is None
    # ...the selected seal site crashes.
    with pytest.raises(faults.InjectedCrash):
        faults.fire("snapshot_seal")


def test_cluster_seal_crash_exactly_once(tmp_path):
    """2-process cluster: a ``snapshot_seal`` crash on worker 0 with
    async+delta on kills it between seal and commit; the peers'
    supervisors restart, the mesh re-forms, and the completed run's
    output equals the fault-free oracle exactly-once."""
    from tests.test_chaos import _run_seq_cluster, _seq_oracle

    cap = 30
    res, out_path = _run_seq_cluster(
        tmp_path,
        "ckpt_seal",
        cap,
        {
            "BYTEWAX_TPU_CKPT_ASYNC": "1",
            "BYTEWAX_TPU_CKPT_DELTA": "1",
            "BYTEWAX_TPU_FAULTS": "snapshot_seal:crash:3:0:x1",
            "BYTEWAX_TPU_MAX_RESTARTS": "3",
            "BYTEWAX_TPU_RESTART_BACKOFF_S": "0.1",
            "BYTEWAX_TPU_EPOCH_STALL_S": "15",
            "CHAOS_PACE_S": "0.01",
        },
    )
    assert res.returncode == 0, res.stderr[-3000:]
    assert "supervised restart" in res.stderr, res.stderr[-3000:]
    assert sorted(out_path.read_text().split()) == _seq_oracle(cap)


# -- delta rows: latest-row-wins, fewer writes, compaction -------------


def test_delta_latest_row_wins_across_cold_keys(
    tmp_path, monkeypatch
):
    """A key untouched for many epochs keeps only its old row under
    delta mode; resume reconstitutes it from that row (latest-row-
    per-key) while hot keys resume from their newest.  Under a
    retain-everything store the delta run writes strictly fewer
    snaps rows than the full-snapshot run of the same flow."""
    # "cold" is touched once up front; "hot" every delivery after.
    head = [("cold", 5)] + [("hot", i) for i in range(8)]
    tail = [("cold", 7), ("hot", 100)]
    oracle = _running_sum_oracle(head + tail)

    rows = {}
    for mode in ("delta", "full"):
        # Fresh ABORT per mode: the sentinel is single-use.
        inp = head + [TestingSource.ABORT()] + tail
        _ckpt_env(monkeypatch, async_=False, delta=(mode == "delta"))
        db = _mk_db(tmp_path, f"db_{mode}")
        cfg = RecoveryConfig(str(db), backup_interval=RETAIN_TD)
        out_path = tmp_path / f"out_{mode}.txt"
        # FileSink truncates to the snapshotted offset on resume, so
        # the abort/replay pair is exactly-once at the sink.
        run_main(
            _file_flow(inp, str(out_path)),
            epoch_interval=ZERO_TD,
            recovery_config=cfg,
        )
        run_main(
            _file_flow(inp, str(out_path)),
            epoch_interval=ZERO_TD,
            recovery_config=cfg,
        )
        rows[mode] = _snaps_rows(db)
        # Resume semantics identical to the full-snapshot engine —
        # including cold=12 (5 from the pre-abort row plus the
        # replayed 7, reconstituted latest-row-per-key).
        assert sorted(out_path.read_text().split()) == oracle
    # The delta store skipped the unchanged-key rewrites.
    assert len(rows["delta"]) < len(rows["full"])
    # ...and the cold key's chain stays short: one row per epoch it
    # actually changed in (plus at most a replayed rewrite).
    cold_epochs = {
        e
        for (_s, k, e, _r, b) in rows["delta"]
        if k == "cold" and b is not None
    }
    assert len(cold_epochs) <= 3


def test_compaction_bounds_retained_delta_chain(
    tmp_path, monkeypatch
):
    """BYTEWAX_TPU_CKPT_COMPACT_EVERY forces a commit/GC watermark
    every K closes even under a retain-everything backup interval:
    resume state is identical, the chain is strictly shorter."""
    head = [("hot", i) for i in range(10)]
    tail = [("hot", 100)]
    oracle = _running_sum_oracle(head + tail)
    rows = {}
    for mode, compact in (("plain", None), ("compact", 2)):
        # Fresh ABORT per mode: the sentinel is single-use.
        inp = head + [TestingSource.ABORT()] + tail
        _ckpt_env(
            monkeypatch, async_=False, delta=True, compact=compact
        )
        db = _mk_db(tmp_path, f"db_{mode}")
        cfg = RecoveryConfig(str(db), backup_interval=RETAIN_TD)
        out_path = tmp_path / f"out_{mode}.txt"
        run_main(
            _file_flow(inp, str(out_path)),
            epoch_interval=ZERO_TD,
            recovery_config=cfg,
        )
        run_main(
            _file_flow(inp, str(out_path)),
            epoch_interval=ZERO_TD,
            recovery_config=cfg,
        )
        rows[mode] = _snaps_rows(db)
        assert sorted(out_path.read_text().split()) == oracle
    assert len(rows["compact"]) < len(rows["plain"])


def test_cross_tier_recovery_with_state_budget(
    recovery_config, tmp_path, monkeypatch
):
    """Delta+async checkpoints read through the residency manager
    like the synchronous path: a budgeted device-tier run whose keys
    are evicted/spilled at the abort resumes to the exact host
    oracle."""
    _ckpt_env(monkeypatch, async_=True, delta=True, compact=3)
    monkeypatch.setenv("BYTEWAX_TPU_STATE_BUDGET", "2")
    monkeypatch.setenv("BYTEWAX_TPU_HOST_STATE_BUDGET", "3")
    monkeypatch.setenv(
        "BYTEWAX_TPU_SPILL_DIR", str(tmp_path / "spill")
    )
    head = [(f"k{(i * 7) % 12:02d}", i) for i in range(60)]
    tail = [(f"k{(i * 5) % 12:02d}", i) for i in range(24)]
    inp = head + [TestingSource.ABORT()] + tail
    flow_id = "ckpt_res"

    def build(out):
        flow = Dataflow(flow_id)
        s = op.input("inp", flow, TestingSource(inp, batch_size=2))
        r = op.reduce_final("sum", s, xla.SUM)
        op.output("out", r, TestingSink(out))
        return flow

    out = []
    run_main(
        build(out),
        epoch_interval=ZERO_TD,
        recovery_config=recovery_config,
    )
    assert out == []  # reduce_final emits at EOF only
    out2 = []
    run_main(
        build(out2),
        epoch_interval=ZERO_TD,
        recovery_config=recovery_config,
    )
    sums = {}
    for k, v in head + tail:
        sums[k] = sums.get(k, 0) + v
    assert sorted(out2) == sorted(sums.items())


def test_rescale_migrates_uncompacted_delta_chain(tmp_path):
    """`rescale_snaps_rows` re-stamps EVERY row of an uncompacted
    delta chain — a cold key's single old row and a hot key's whole
    epoch chain — and route-scoped latest-per-key reads stay a
    disjoint exact cover under the new modulus."""
    init_db_dir(tmp_path, 2)
    store = RecoveryStore(tmp_path)
    store.write_ex_started(0, 2, 1)
    # Epoch 1 writes everything; epochs 2-4 are delta closes that
    # touch only the hot keys.  commit_epoch=None retains the chain.
    hot = [f"hot{i:02d}" for i in range(8)]
    cold = [f"cold{i:02d}" for i in range(8)]
    store.write_epoch(
        0,
        2,
        1,
        [("df.s", k, pickle.dumps(0)) for k in hot + cold],
        None,
    )
    for epoch in (2, 3, 4):
        store.write_epoch(
            0,
            2,
            epoch,
            [("df.s", k, pickle.dumps(epoch)) for k in hot],
            None,
        )
    migrated = store.rescale(3, ex_num=0)
    assert migrated == len(hot) + len(cold)
    for part in sorted(Path(tmp_path).glob("part-*.sqlite3")):
        con = sqlite3.connect(part)
        try:
            for key, route in con.execute(
                "SELECT state_key, route FROM snaps"
            ):
                assert route == route_of(key, 3)
        finally:
            con.close()
    # Latest-per-key under the new routing: hot keys read epoch 4,
    # cold keys their epoch-1 row; the per-lane reads are a disjoint
    # exact cover.
    by_lane = {
        w: {
            k: pickle.loads(b)
            for _s, k, b in store.iter_snaps(5, routes=[w])
        }
        for w in range(3)
    }
    merged = {}
    for lane in by_lane.values():
        for k in lane:
            assert k not in merged, f"key {k} read by two lanes"
        merged.update(lane)
    assert merged == dict(
        {k: 4 for k in hot}, **{k: 0 for k in cold}
    )
    assert store.resume_from(worker_count=3).resume_epoch == 5
    store.close()


# -- observability: /status, /healthz, the hint ------------------------


def test_status_and_healthz_expose_committer_lane(
    tmp_path, monkeypatch
):
    """/status carries the checkpoint section (durable vs sealed
    epoch), /healthz stays green at lag <= 1 and degrades above —
    readiness drops with a distinct state while liveness holds."""
    from bytewax_tpu.engine import driver as drv

    _ckpt_env(monkeypatch, async_=True, delta=True)
    seen = {}
    orig = drv._Driver._close_epoch

    def spy(self, workers=None):
        if "status" not in seen:
            seen["status"] = self._status()
            seen["health"] = self._health()
            # Force a lagging committer lane (payload builders only
            # — no engine behavior changes) and read /healthz again.
            sealed = self._ckpt_sealed_epoch
            self._ckpt_sealed_epoch = self._durable_epoch + 2
            seen["health_lagging"] = self._health()
            self._ckpt_sealed_epoch = sealed
        return orig(self, workers)

    monkeypatch.setattr(drv._Driver, "_close_epoch", spy)
    db = _mk_db(tmp_path, "db")
    out = []
    flow = Dataflow("ckpt_status_df")
    s = op.input("inp", flow, TestingSource([1, 2, 3]))
    op.output("out", s, TestingSink(out))
    run_main(
        flow,
        epoch_interval=ZERO_TD,
        recovery_config=RecoveryConfig(str(db)),
    )
    ck = seen["status"]["checkpoint"]
    assert ck["async"] is True and ck["delta"] is True
    assert ck["lag_epochs"] <= 1
    assert ck["sealed_epoch"] - ck["durable_epoch"] == ck["lag_epochs"]
    health = seen["health"]
    assert health["ready"] is True
    assert health["snapshot_lag_epochs"] <= 1
    lagging = seen["health_lagging"]
    assert lagging["ready"] is False
    assert lagging["state"] == "checkpoint_lagging"
    assert lagging["snapshot_lag_epochs"] == 2


def test_rescale_hint_snapshot_stall_is_grow_and_blocks_shrink():
    """Fence stalls are durability pressure: loud ones are their own
    grow reason, and a non-quiet committer lane blocks shrink — so
    async checkpointing (which legitimately shrinks close p99) can
    never read as a shrink signal by itself."""
    advice, reasons = derive_rescale_hint(
        worker_count=2,
        epoch_interval_s=10.0,
        close_p99_s=0.1,
        stall_s_per_close=0.0,
        restores_per_close=0.0,
        snapshot_stall_s_per_close=3.0,
    )
    assert advice == "grow"
    assert any("checkpoint durability" in r for r in reasons)
    # Not loud enough to grow, not quiet enough to shrink: hold.
    advice, _ = derive_rescale_hint(
        worker_count=4,
        epoch_interval_s=10.0,
        close_p99_s=0.1,
        stall_s_per_close=0.0,
        restores_per_close=0.0,
        snapshot_stall_s_per_close=0.5,
    )
    assert advice == "hold"
    # A genuinely quiet lane leaves the shrink path untouched.
    advice, _ = derive_rescale_hint(
        worker_count=4,
        epoch_interval_s=10.0,
        close_p99_s=0.1,
        stall_s_per_close=0.0,
        restores_per_close=0.0,
        snapshot_stall_s_per_close=0.0,
    )
    assert advice == "shrink"
