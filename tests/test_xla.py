"""XLA-tier tests: columnar batches, device aggregation, mesh
exchange.  Run on the virtual 8-device CPU mesh from conftest."""

import numpy as np
import pytest

import bytewax_tpu.operators as op
from bytewax_tpu import xla
from bytewax_tpu.dataflow import Dataflow
from bytewax_tpu.engine.arrays import ArrayBatch
from bytewax_tpu.engine.xla import DeviceAggState
from bytewax_tpu.inputs import DynamicSource, StatelessSourcePartition
from bytewax_tpu.testing import TestingSink, TestingSource, run_main


class _ArraySourcePartition(StatelessSourcePartition):
    def __init__(self, batches):
        self._batches = list(batches)

    def next_batch(self):
        if not self._batches:
            raise StopIteration()
        return self._batches.pop(0)


class ArraySource(DynamicSource):
    """Emit pre-built ArrayBatch columnar batches (worker 0 only)."""

    def __init__(self, batches):
        self._batches = batches

    def build(self, step_id, worker_index, worker_count):
        if worker_index == 0:
            return _ArraySourcePartition(self._batches)
        return _ArraySourcePartition([])


def test_array_batch_to_pylist_kv():
    ab = ArrayBatch({"key": np.array(["a", "b"]), "value": np.array([1, 2])})
    assert ab.to_pylist() == [("a", 1), ("b", 2)]
    assert len(ab) == 2


def test_columnar_reduce_final_sum():
    batches = [
        ArrayBatch(
            {
                "key": np.array(["a", "b", "a"]),
                "value": np.array([1.0, 10.0, 2.0]),
            }
        ),
        ArrayBatch(
            {"key": np.array(["b"]), "value": np.array([30.0])}
        ),
    ]
    out = []
    flow = Dataflow("test_df")
    s = op.input("inp", flow, ArraySource(batches))
    r = op.reduce_final("sum", s, xla.SUM)
    op.output("out", r, TestingSink(out))
    run_main(flow)
    assert sorted(out) == [("a", 3.0), ("b", 40.0)]


def test_columnar_jax_udf_map():
    batches = [
        ArrayBatch(
            {"key": np.array(["a", "a"]), "value": np.array([1.0, 2.0])}
        )
    ]
    out = []

    @xla.jit_batch
    def double(cols):
        # String columns (key) bypass the jitted fn and re-attach.
        return {"value": cols["value"] * 2}

    flow = Dataflow("test_df")
    s = op.input("inp", flow, ArraySource(batches))
    s = op.flat_map_batch("double", s, double)
    r = op.reduce_final("sum", s, xla.SUM)
    op.output("out", r, TestingSink(out))
    run_main(flow)
    assert out == [("a", 6.0)]


def test_jax_udf_rejects_python_items():
    @xla.jit_batch
    def ident(cols):
        return cols

    out = []
    flow = Dataflow("test_df")
    s = op.input("inp", flow, TestingSource([1, 2]))
    s = op.flat_map_batch("bad", s, ident)
    op.output("out", s, TestingSink(out))
    with pytest.raises(TypeError, match="ArrayBatch"):
        run_main(flow)


def test_accelerated_count_matches_host(monkeypatch):
    inp = ["apple", "banana", "apple", "banana", "banana"]

    def run(accel_env):
        monkeypatch.setenv("BYTEWAX_TPU_ACCEL", accel_env)
        out = []
        flow = Dataflow("test_df")
        s = op.input("inp", flow, TestingSource(inp))
        s = op.count_final("count", s, lambda x: x)
        op.output("out", s, TestingSink(out))
        run_main(flow)
        return sorted(out)

    assert run("1") == run("0") == [("apple", 2), ("banana", 3)]


def test_accelerated_min_max_fallback_non_numeric(monkeypatch):
    monkeypatch.setenv("BYTEWAX_TPU_ACCEL", "1")
    inp = [("k", "zebra"), ("k", "ant")]
    out = []
    flow = Dataflow("test_df")
    s = op.input("inp", flow, TestingSource(inp))
    s = op.min_final("min", s)
    op.output("out", s, TestingSink(out))
    run_main(flow)
    assert out == [("k", "ant")]


def test_stats_final():
    inp = [("k", 1.0), ("k", 2.0), ("k", 9.0), ("j", 5.0)]
    out = []
    flow = Dataflow("test_df")
    s = op.input("inp", flow, TestingSource(inp))
    s = xla.stats_final("stats", s)
    op.output("out", s, TestingSink(out))
    run_main(flow)
    assert sorted(out) == [
        ("j", (5.0, 5.0, 5.0, 1)),
        ("k", (1.0, 4.0, 9.0, 3)),
    ]


def test_accelerated_recovery_cross_tier(tmp_path, monkeypatch):
    # Crash mid-stream with the device tier, resume with the host
    # tier (and vice versa): snapshots are interchangeable.
    from bytewax_tpu.recovery import RecoveryConfig, init_db_dir
    from datetime import timedelta

    init_db_dir(tmp_path, 1)
    rc = RecoveryConfig(str(tmp_path))
    inp = [
        ("a", 5),
        ("a", 3),
        TestingSource.ABORT(),
        ("a", 40),
    ]
    out = []
    flow = Dataflow("test_df")
    s = op.input("inp", flow, TestingSource(inp))
    s = op.max_final("max", s)
    op.output("out", s, TestingSink(out))

    monkeypatch.setenv("BYTEWAX_TPU_ACCEL", "1")
    run_main(flow, epoch_interval=timedelta(0), recovery_config=rc)
    assert out == []

    monkeypatch.setenv("BYTEWAX_TPU_ACCEL", "0")
    run_main(flow, epoch_interval=timedelta(0), recovery_config=rc)
    assert out == [("a", 40)]


def test_device_agg_state_growth():
    agg = DeviceAggState("sum")
    n = 5000  # > initial capacity, forces growth
    keys = np.array([f"k{i:05d}" for i in range(n)])
    values = np.ones(n, dtype=np.float32)
    agg.update(keys, values)
    agg.update(keys, values)
    results = dict(agg.finalize())
    assert len(results) == n
    assert results["k00000"] == 2.0
    assert results[f"k{n - 1:05d}"] == 2.0


def test_keyed_all_to_all_mesh():
    import jax
    import jax.numpy as jnp

    from bytewax_tpu.parallel.exchange import keyed_all_to_all
    from bytewax_tpu.parallel.mesh import make_mesh

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    mesh = make_mesh(8)
    n = 64  # 8 rows per device
    rng = np.random.RandomState(0)
    shard_ids = rng.randint(0, 8, size=n).astype(np.int32)
    values = np.arange(n, dtype=np.float32)
    valid = np.ones(n, dtype=bool)

    got, mask, dropped = keyed_all_to_all(
        mesh, 16, jnp.asarray(shard_ids), jnp.asarray(values), jnp.asarray(valid)
    )
    got = np.asarray(got)
    mask = np.asarray(mask)
    assert int(dropped) == 0
    # After exchange, device d's slice holds exactly the rows whose
    # shard_id == d.
    per_dev = got.reshape(8, -1)
    per_mask = mask.reshape(8, -1)
    for d in range(8):
        received = sorted(per_dev[d][per_mask[d]].tolist())
        expected = sorted(values[shard_ids == d].tolist())
        assert received == expected, f"device {d}"


def test_keyed_all_to_all_reports_drops():
    # An undersized bucket capacity must be detectable: the exchange
    # reports how many valid rows did not fit instead of silently
    # losing them.
    import jax
    import jax.numpy as jnp

    from bytewax_tpu.parallel.exchange import keyed_all_to_all
    from bytewax_tpu.parallel.mesh import make_mesh

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    mesh = make_mesh(8)
    n = 64
    shard_ids = np.zeros(n, dtype=np.int32)  # every row to shard 0
    values = np.arange(n, dtype=np.float32)
    valid = np.ones(n, dtype=bool)
    got, mask, dropped = keyed_all_to_all(
        mesh, 4, jnp.asarray(shard_ids), jnp.asarray(values), jnp.asarray(valid)
    )
    # 8 rows per source device, capacity 4 -> 4 dropped per source.
    assert int(dropped) == 32
    assert int(np.asarray(mask).sum()) == 32


def test_int64_overflow_falls_back_to_host():
    big = 1 << 40
    inp = [("k", big), ("k", big)]
    out = []
    flow = Dataflow("test_df")
    s = op.input("inp", flow, TestingSource(inp))
    r = op.reduce_final("sum", s, xla.SUM)
    op.output("out", r, TestingSink(out))
    run_main(flow)
    assert out == [("k", 2 * big)]  # exact, via host fallback


def test_value_scale_string_key_path():
    ab = ArrayBatch(
        {"key": np.array(["a", "a"]), "value": np.array([15, 23], np.int16)},
        value_scale=0.1,
    )
    agg = DeviceAggState("sum")
    agg.update_batch(ab)
    results = dict(agg.finalize())
    assert abs(results["a"] - 3.8) < 1e-5
    # to_pylist honors the scale too
    assert ab.to_pylist()[0] == ("a", 1.5)


def test_vocab_must_be_append_only():
    agg = DeviceAggState("sum")
    v1 = np.array(["london", "paris"])
    v2 = np.array(["paris", "london"])  # reordered — invalid
    agg.update_batch(
        ArrayBatch(
            {"key_id": np.array([0], np.int16), "value": np.array([1.0])},
            key_vocab=v1,
        )
    )
    with pytest.raises(TypeError, match="append-only"):
        agg.update_batch(
            ArrayBatch(
                {"key_id": np.array([0], np.int16), "value": np.array([1.0])},
                key_vocab=v2,
            )
        )


def test_redistributed_columnar_batch_reaches_accel(monkeypatch):
    # Strided per-lane column views from a columnar redistribute must
    # still run the device-accelerated keyed fold (KeyEncoder compacts
    # non-contiguous key columns before its dtype view).
    monkeypatch.setenv("BYTEWAX_TPU_ACCEL", "1")
    import bytewax_tpu.operators as op
    from bytewax_tpu.dataflow import Dataflow
    from bytewax_tpu.engine.arrays import ArrayBatch
    from bytewax_tpu.xla import SUM

    keys = np.array([f"k{i % 3}" for i in range(300)])
    batch = ArrayBatch({"key": keys, "value": np.ones(300)})
    out = []
    flow = Dataflow("test_df")
    s = op.input("inp", flow, ArraySource([batch]))
    s = op.redistribute("shuffle", s)
    r = op.reduce_final("sum", s, SUM)
    op.output("out", r, TestingSink(out))
    from bytewax_tpu.testing import cluster_main

    cluster_main(flow, [], 0, worker_count_per_proc=2)
    assert sorted(out) == [("k0", 100.0), ("k1", 100.0), ("k2", 100.0)]


def test_key_encoder_empty_first_batch():
    # An empty delivery must not install its (arbitrary) dtype kind
    # as the encoder's seen-set; later real batches keep the
    # steady-state fast path.
    from bytewax_tpu.engine.arrays import KeyEncoder

    enc = KeyEncoder()
    assert len(enc.encode(np.array([], dtype=object), lambda ks: [])) == 0
    assert enc._sorted is None
    ids = enc.encode(np.array(["a", "b", "a"]), lambda ks: [10, 11])
    assert ids.tolist() == [10, 11, 10]
    assert enc._sorted is not None and enc._sorted.dtype.kind == "U"
    # Steady state: no allocs for seen keys.
    ids2 = enc.encode(np.array(["b", "a"]), lambda ks: 1 / 0)
    assert ids2.tolist() == [11, 10]


def test_key_encoder_wide_column_fast_path():
    """With few seen keys, an over-wide string column is searched
    as-is (no per-batch narrowing); prefix collisions and misses stay
    exact across widths."""
    from bytewax_tpu.engine.arrays import KeyEncoder

    enc = KeyEncoder()
    next_id = iter(range(100))
    alloc = lambda ks: [next(next_id) for _ in ks]  # noqa: E731

    ids = enc.encode(np.array(["a", "b"], dtype="U1"), alloc)
    assert ids.tolist() == [0, 1]
    assert enc._sorted.dtype.itemsize // 4 == 1  # stored narrow

    # Over-wide batch (U8): hits map to the same ids; "ab" must MISS
    # (no truncation against the narrow "a") and get a fresh id.
    wide = np.array(["b", "ab", "a"], dtype="U8")
    ids2 = enc.encode(wide, alloc)
    assert ids2.tolist() == [1, 2, 0]
    # The miss installed narrowed: the seen set stays at true width.
    assert enc._sorted.dtype.itemsize // 4 == 2
    # Steady state over wide columns: no allocs.
    ids3 = enc.encode(np.array(["ab", "a", "b"], dtype="U21"), lambda ks: 1 / 0)
    assert ids3.tolist() == [2, 0, 1]


def test_key_encoder_many_keys_still_narrow():
    """Above the wide-search threshold the narrowing path still runs
    (deep searches at full width would be slower) and stays exact."""
    from bytewax_tpu.engine.arrays import KeyEncoder

    enc = KeyEncoder()
    keys = np.array([f"k{i}" for i in range(40)])
    ids = enc.encode(keys, lambda ks: list(range(len(ks))))
    wide = keys.astype("U30")
    ids2 = enc.encode(wide, lambda ks: 1 / 0)
    assert ids2.tolist() == ids.tolist()
