"""Tiered key-state residency (engine/residency.py): budgeted HBM,
host-RAM eviction, disk spill.

The host tier (BYTEWAX_TPU_ACCEL=0 / plain Python sums) is the
oracle: a budgeted run must produce identical output however many
evictions, restores, and spills happened along the way, the resident
device key count must hold the budget at every drain boundary, and
recovery must cover evicted/spilled keys unchanged.  Faults are
injected ONLY through the engine's own injector (the pinned
``residency_restore`` site) — no monkeypatching of engine internals.
"""

import os
import pickle
import sqlite3
from datetime import timedelta
from pathlib import Path

import numpy as np
import pytest

import bytewax_tpu.operators as op
from bytewax_tpu import xla
from bytewax_tpu.dataflow import Dataflow
from bytewax_tpu.engine import faults, flight
from bytewax_tpu.testing import TestingSink, TestingSource, run_main

ZERO_TD = timedelta(seconds=0)


@pytest.fixture(autouse=True)
def _fresh_fault_plan():
    faults.reset()
    yield
    faults.reset()


def _sum_flow(flow_id, inp, out, batch_size=2):
    flow = Dataflow(flow_id)
    s = op.input("inp", flow, TestingSource(inp, batch_size=batch_size))
    r = op.reduce_final("sum", s, xla.SUM)
    op.output("out", r, TestingSink(out))
    return flow


def _sum_oracle(inp):
    sums = {}
    for k, v in inp:
        sums[k] = sums.get(k, 0) + v
    return sorted(sums.items())


def _cycling_items(n, n_keys, stride=7):
    """Every key recurs throughout the stream, so a small budget
    forces continuous evict/restore churn."""
    return [(f"k{(i * stride) % n_keys:03d}", i) for i in range(n)]


def _peak_resident(flow_id):
    return max(
        (
            v
            for k, v in flight.RECORDER.counters.items()
            if k.startswith("state_resident_keys_peak[")
            and flow_id in k
        ),
        default=0,
    )


# -- eviction/restore output equality vs the host oracle --------------------


@pytest.mark.parametrize("budget", [2, 8, None])
def test_budgeted_agg_matches_host_oracle(
    entry_point, entry_point_name, budget, monkeypatch, tmp_path
):
    """Aggregation outputs are identical to the host oracle at tight,
    loose, and unbounded budgets, under all three entry points.
    Integer values keep both tiers exact, so equality is exact."""
    flow_id = f"res_eq_{budget}_{entry_point_name}"
    if budget is not None:
        monkeypatch.setenv("BYTEWAX_TPU_STATE_BUDGET", str(budget))
        monkeypatch.setenv(
            "BYTEWAX_TPU_SPILL_DIR", str(tmp_path / "spill")
        )
        monkeypatch.setenv("BYTEWAX_TPU_HOST_STATE_BUDGET", "4")
    else:
        monkeypatch.delenv("BYTEWAX_TPU_STATE_BUDGET", raising=False)
    inp = _cycling_items(240, 24)
    out = []
    entry_point(_sum_flow(flow_id, inp, out), epoch_interval=ZERO_TD)
    assert sorted(out) == _sum_oracle(inp)
    if budget is not None:
        # deliveries carry at most 2 distinct keys <= every budget
        # tested, so the boundary invariant must hold exactly.
        assert 0 < _peak_resident(flow_id) <= budget


def test_budget_invariant_and_tier_counters(monkeypatch, tmp_path):
    """With cardinality >> budget the run evicts, restores, and
    spills — and resident keys never exceed the budget at any drain
    boundary (the ratcheting peak counter under the
    bytewax_state_resident_keys family is the audit)."""
    flow_id = "res_invariant"
    monkeypatch.setenv("BYTEWAX_TPU_STATE_BUDGET", "3")
    monkeypatch.setenv("BYTEWAX_TPU_SPILL_DIR", str(tmp_path / "spill"))
    monkeypatch.setenv("BYTEWAX_TPU_HOST_STATE_BUDGET", "4")
    c0 = dict(flight.RECORDER.counters)
    inp = _cycling_items(200, 20)
    out = []
    run_main(_sum_flow(flow_id, inp, out), epoch_interval=ZERO_TD)
    assert sorted(out) == _sum_oracle(inp)

    def delta(name):
        return flight.RECORDER.counters.get(name, 0) - c0.get(name, 0)

    assert delta("state_evictions_count") > 0
    assert delta("residency_restore_count") > 0
    assert delta("state_spill_bytes") > 0
    peak = _peak_resident(flow_id)
    assert 0 < peak <= 3
    # The Prometheus gauge tracks the same samples.
    from bytewax_tpu._metrics import state_resident_keys

    gauge_vals = [
        s.value
        for metric in state_resident_keys.collect()
        for s in metric.samples
        if flow_id in str(s.labels.get("step_id", ""))
    ]
    assert gauge_vals and max(gauge_vals) <= 3


def test_unset_budget_never_builds_a_manager(monkeypatch):
    """Depth-0 contract: without BYTEWAX_TPU_STATE_BUDGET the state
    object the driver folds into is the raw tier — no wrapper, no
    manager code on any path."""
    monkeypatch.delenv("BYTEWAX_TPU_STATE_BUDGET", raising=False)
    from bytewax_tpu.engine.residency import maybe_wrap
    from bytewax_tpu.engine.sharded_state import make_agg_state

    st = make_agg_state("sum")
    assert maybe_wrap("step", st) is st


# -- scan tier ---------------------------------------------------------------


def test_budgeted_scan_matches_host_oracle(monkeypatch, tmp_path):
    """The per-row-emitting scan tier restores evicted key state
    BEFORE folding (outputs read the state), so per-row emissions
    match the host mapper exactly under a tight budget."""
    monkeypatch.setenv("BYTEWAX_TPU_STATE_BUDGET", "2")
    monkeypatch.setenv("BYTEWAX_TPU_SPILL_DIR", str(tmp_path / "spill"))
    monkeypatch.setenv("BYTEWAX_TPU_HOST_STATE_BUDGET", "3")
    items = [
        (f"k{(i * 3) % 9}", float(np.round(np.sin(i), 3)))
        for i in range(120)
    ]

    def make():
        return xla.ema(0.5)

    states = {}
    want = []
    mapper = make()
    for k, v in items:
        st, emit = mapper(states.get(k), v)
        states[k] = st
        want.append((k, emit))

    out = []
    flow = Dataflow("res_scan")
    s = op.input("inp", flow, TestingSource(items, batch_size=2))
    s = op.stateful_map("scan", s, make())
    op.output("out", s, TestingSink(out))
    run_main(flow, epoch_interval=ZERO_TD)

    by_g, by_w = {}, {}
    for k, row in out:
        by_g.setdefault(k, []).append(row)
    for k, row in want:
        by_w.setdefault(k, []).append(row)
    assert by_g.keys() == by_w.keys()
    for k in by_w:
        for g_row, w_row in zip(by_g[k], by_w[k]):
            assert g_row[0] == pytest.approx(w_row[0])
            assert g_row[1] == pytest.approx(w_row[1], abs=1e-4)
    assert _peak_resident("res_scan") <= 2


# -- spilled-key recovery via resume_from() ----------------------------------


def test_spilled_key_recovery_resume_from(
    recovery_config, tmp_path, monkeypatch
):
    """Epoch snapshots read THROUGH the residency tiers, so a key
    sitting in the disk spill store when the run aborts resumes via
    resume_from() exactly like a resident one."""
    monkeypatch.setenv("BYTEWAX_TPU_STATE_BUDGET", "2")
    monkeypatch.setenv("BYTEWAX_TPU_HOST_STATE_BUDGET", "3")
    spill_dir = tmp_path / "spill"
    monkeypatch.setenv("BYTEWAX_TPU_SPILL_DIR", str(spill_dir))
    head = _cycling_items(90, 18)
    tail = _cycling_items(36, 18, stride=5)
    inp = head + [TestingSource.ABORT()] + tail
    out = []
    flow_id = "res_resume"
    run_main(
        _sum_flow(flow_id, inp, out),
        epoch_interval=ZERO_TD,
        recovery_config=recovery_config,
    )
    assert out == []  # reduce_final emits at EOF only

    # The spill tier engaged and its rows ARE recovery-format rows:
    # same snaps schema, pickled host-format state.
    files = list(Path(spill_dir).glob("spill-*.sqlite3"))
    assert files, "expected a spill store file"
    con = sqlite3.connect(files[0])
    try:
        rows = con.execute(
            "SELECT step_id, state_key, epoch, ser_change FROM snaps"
        ).fetchall()
    finally:
        con.close()
    assert rows, "expected spilled rows in recovery row format"
    for sid, key, _epoch, ser in rows:
        assert "stateful_batch" in sid
        assert isinstance(pickle.loads(ser), int)

    out2 = []
    run_main(
        _sum_flow(flow_id, inp, out2),
        epoch_interval=ZERO_TD,
        recovery_config=recovery_config,
    )
    assert sorted(out2) == _sum_oracle(head + tail)


# -- residency faults through the real injector ------------------------------


def test_mid_restore_device_fault_retries_in_place(
    monkeypatch, tmp_path
):
    """A DeviceFault injected at the pinned residency_restore site
    (fired BEFORE any state mutates) is retried in place by the
    driver's dispatch handling; output stays equal to the oracle."""
    flow_id = "res_fault_retry"
    monkeypatch.setenv("BYTEWAX_TPU_STATE_BUDGET", "2")
    monkeypatch.setenv("BYTEWAX_TPU_SPILL_DIR", str(tmp_path / "spill"))
    monkeypatch.setenv(
        "BYTEWAX_TPU_FAULTS", "residency_restore:error:*:x1"
    )
    c0 = flight.RECORDER.counters.get("fault_injected_count", 0)
    inp = _cycling_items(120, 12)
    out = []
    run_main(_sum_flow(flow_id, inp, out), epoch_interval=ZERO_TD)
    assert sorted(out) == _sum_oracle(inp)
    assert (
        flight.RECORDER.counters.get("fault_injected_count", 0)
        == c0 + 1
    )


def test_persistent_restore_faults_demote_with_all_tiers(
    monkeypatch, tmp_path
):
    """Restore faults past the demotion threshold demote the step to
    the host tier; demotion_snapshots drains the resident, evicted,
    AND spilled tiers, so the migrated host logics own every key and
    the output still matches the oracle."""
    flow_id = "res_fault_demote"
    monkeypatch.setenv("BYTEWAX_TPU_STATE_BUDGET", "2")
    monkeypatch.setenv("BYTEWAX_TPU_SPILL_DIR", str(tmp_path / "spill"))
    monkeypatch.setenv("BYTEWAX_TPU_HOST_STATE_BUDGET", "3")
    monkeypatch.setenv(
        "BYTEWAX_TPU_FAULTS", "residency_restore:error:*"
    )
    c0 = flight.RECORDER.counters.get("demotion_count", 0)
    inp = _cycling_items(120, 12)
    out = []
    run_main(_sum_flow(flow_id, inp, out), epoch_interval=ZERO_TD)
    assert sorted(out) == _sum_oracle(inp)
    assert (
        flight.RECORDER.counters.get("demotion_count", 0) == c0 + 1
    )


# -- the collective tier never evicts ----------------------------------------


def test_global_exchange_tier_never_evicts(monkeypatch):
    """Pin: the global-mesh exchange tier is excluded from residency
    exactly like demotion — maybe_wrap refuses global_exchange states
    even with a budget armed, and GlobalAggState implements no
    residency surface (the BTX-SNAPSHOT rule proves the same over
    the AST)."""
    monkeypatch.setenv("BYTEWAX_TPU_STATE_BUDGET", "2")
    from bytewax_tpu.engine.residency import maybe_wrap
    from bytewax_tpu.engine.sharded_state import GlobalAggState

    class _FakeGlobal:
        global_exchange = True

    fake = _FakeGlobal()
    assert maybe_wrap("step", fake) is fake
    assert not hasattr(GlobalAggState, "extract_keys")
    assert not hasattr(GlobalAggState, "inject_keys")


# -- extract/inject unit round trips -----------------------------------------


def test_agg_extract_inject_round_trip():
    from bytewax_tpu.engine.sharded_state import make_agg_state

    st = make_agg_state("sum")
    st.update(
        np.asarray(["a", "b", "c"]), np.asarray([1, 2, 3])
    )
    items = dict(st.extract_keys(["a", "b"]))
    assert items == {"a": 1, "b": 2}
    assert set(st.keys()) == {"c"}
    st.inject_keys(list(items.items()))
    st.update(np.asarray(["a"]), np.asarray([10]))
    assert sorted(st.finalize()) == [("a", 11), ("b", 2), ("c", 3)]


def test_scan_extract_inject_round_trip():
    from bytewax_tpu.engine.sharded_state import make_scan_state
    from bytewax_tpu.ops.scan import Ema

    st = make_scan_state(Ema(0.5))
    st.update(
        np.asarray(["a", "a", "b"]), np.asarray([1.0, 2.0, 3.0])
    )
    items = st.extract_keys(["a"])
    assert [k for k, _s in items] == ["a"]
    (snap,) = [s for _k, s in items]
    assert snap[0] == 2  # count field rode the snapshot
    assert "a" not in st.keys()
    st.inject_keys(items)
    (resumed,) = [s for _k, s in st.snapshots_for(["a"])]
    assert resumed == pytest.approx(snap)


def test_window_extract_inject_round_trip():
    """The window tier's residency surface: extraction drains a key's
    open windows to its host-format _WindowSnapshot and frees the
    fold slots; injection reinstates them bit-for-bit."""
    from datetime import datetime, timedelta, timezone

    from bytewax_tpu.engine.window_accel import WindowAccelSpec

    align = datetime(2024, 1, 1, tzinfo=timezone.utc)
    spec = WindowAccelSpec(
        "sum",
        lambda v: v.ts,
        align,
        timedelta(seconds=10),
        timedelta(seconds=10),
        timedelta(seconds=0),
    )
    st = spec.make_state()
    from bytewax_tpu.engine.arrays import TsValue

    ts = align + timedelta(seconds=1)
    _late, phase = st.on_batch(
        ["a", "b"], [TsValue(2.0, ts), TsValue(5.0, ts)]
    )
    phase()
    before = dict(st.snapshots_for(["a"]))
    items = st.extract_keys(["a"])
    assert [k for k, _s in items] == ["a"]
    assert not any(
        k2 == st.key_ids["a"] for (k2, _w) in st.open_close_us
    )
    st.inject_keys(items)
    after = dict(st.snapshots_for(["a"]))
    assert after["a"].logic_states == before["a"].logic_states
    assert (
        after["a"].windower_state.opened
        == before["a"].windower_state.opened
    )
