"""The outer cluster supervisor / autoscaler
(``python -m bytewax_tpu.supervise``; docs/deployment.md "Running
under the autoscaler").

Fast tests pin the pure decision logic (hysteresis, flapping, the
barrier-veto interaction with ``derive_rescale_hint``).  The slow
tests drive REAL multi-process clusters end to end: a grow decision
gracefully drains 2 processes and relaunches 3 (startup migration
re-shards the keyed state), the mirror-image shrink, and a SIGKILLed
child relaunched by the supervisor — in every case total output must
equal the host oracle exactly-once.  Faults are real OS-level faults
(SIGKILL) — no monkeypatching of engine internals, per CLAUDE.md.
"""

import os
import signal
import subprocess
import sys
import threading
import time
from pathlib import Path

import pytest

from bytewax_tpu.engine.driver import derive_rescale_hint
from bytewax_tpu.supervise import (
    ClusterSupervisor,
    decide_scale,
    parse_bounds,
)

# -- pure decision logic ------------------------------------------------


def test_parse_bounds():
    assert parse_bounds("2:8") == (2, 8)
    assert parse_bounds("3") == (3, 3)
    with pytest.raises(ValueError, match="MIN:MAX"):
        parse_bounds("a:b")
    with pytest.raises(ValueError, match="1 <= MIN <= MAX"):
        parse_bounds("4:2")
    with pytest.raises(ValueError, match="1 <= MIN <= MAX"):
        parse_bounds("0:2")


def test_decide_scale_needs_k_consecutive():
    kw = dict(current=2, min_procs=1, max_procs=4, k=3)
    assert decide_scale([], **kw) is None
    assert decide_scale(["grow", "grow"], **kw) is None
    assert decide_scale(["grow", "grow", "grow"], **kw) == 3
    assert decide_scale(["hold", "grow", "grow", "grow"], **kw) == 3
    assert (
        decide_scale(["shrink", "shrink", "shrink"], **kw) == 1
    )


def test_decide_scale_flapping_never_moves():
    # The hint flapping the supervisor must absorb: grow→hold→grow
    # (and grow→shrink alternation) breaks every streak.
    kw = dict(current=2, min_procs=1, max_procs=4, k=2)
    assert decide_scale(["grow", "hold", "grow"], **kw) is None
    assert decide_scale(["grow", "shrink", "grow"], **kw) is None
    assert (
        decide_scale(
            ["grow", "hold", "grow", "hold", "grow"], **kw
        )
        is None
    )
    # ...and only an unbroken tail moves.
    assert decide_scale(["hold", "grow", "grow"], **kw) == 3


def test_decide_scale_respects_bounds():
    assert (
        decide_scale(
            ["grow"] * 3, current=4, min_procs=1, max_procs=4, k=3
        )
        is None
    )
    assert (
        decide_scale(
            ["shrink"] * 3, current=1, min_procs=1, max_procs=4, k=3
        )
        is None
    )
    # One step at a time, even with a long streak.
    assert (
        decide_scale(
            ["grow"] * 10, current=2, min_procs=1, max_procs=8, k=3
        )
        == 3
    )


def test_decide_scale_barrier_veto_interaction():
    # The engine's barrier veto (derive_rescale_hint: a
    # barrier-dominated process's loud signals are skew, not
    # saturation) emits "hold" — which must reset the supervisor's
    # grow streak, so a cluster that goes barrier-bound mid-streak
    # is never grown.
    loud = dict(
        worker_count=2,
        epoch_interval_s=10.0,
        close_p99_s=6.0,
        stall_s_per_close=0.0,
        restores_per_close=0.0,
    )
    advices = [
        derive_rescale_hint(**loud)[0],
        derive_rescale_hint(**loud)[0],
        derive_rescale_hint(
            **loud, phase_fractions={"barrier": 0.7, "host": 0.3}
        )[0],
    ]
    assert advices == ["grow", "grow", "hold"]
    assert (
        decide_scale(
            advices, current=2, min_procs=1, max_procs=4, k=3
        )
        is None
    )
    assert (
        decide_scale(
            advices, current=2, min_procs=1, max_procs=4, k=2
        )
        is None
    )


def test_scaling_bounds_require_recovery_dir():
    # A scale move without a recovery store would be a restart from
    # scratch (empty state, source replayed): refused up front.
    with pytest.raises(ValueError, match="recovery"):
        ClusterSupervisor("x:flow", min_procs=1, max_procs=2)
    # Fixed-size supervision (relaunch-only) stays legal stateless.
    sup = ClusterSupervisor("x:flow", min_procs=2, max_procs=2)
    assert sup.current == 2


# -- real multi-process clusters ----------------------------------------


_SEQ_FLOW = '''
import os
from datetime import datetime, timedelta, timezone

import bytewax_tpu.operators as op
from bytewax_tpu.dataflow import Dataflow
from bytewax_tpu.connectors.files import FileSink
from bytewax_tpu.inputs import FixedPartitionedSource, StatefulSourcePartition


class _Part(StatefulSourcePartition):
    def __init__(self, name, resume):
        self._name = name
        self._i = resume or 0
        self._awake = None

    def next_batch(self):
        if self._i >= int(os.environ["SUPERVISE_CAP"]):
            raise StopIteration()
        self._i += 1
        delay_ms = float(os.environ.get("SUPERVISE_DELAY_MS", "0"))
        if delay_ms:
            self._awake = datetime.now(timezone.utc) + timedelta(
                milliseconds=delay_ms
            )
        return [(f"{{self._name}}-{{self._i % 8}}", float(self._i % 13))]

    def next_awake(self):
        return self._awake

    def snapshot(self):
        return self._i


class SeqSource(FixedPartitionedSource):
    def list_parts(self):
        return ["p0", "p1"]

    def build_part(self, step_id, name, resume):
        return _Part(name, resume)


flow = Dataflow("supervise_df")
s = op.input("inp", flow, SeqSource())
s = op.stateful_map("ema", s, lambda st, v: (
    (v if st is None else st + 0.3 * (v - st),) * 2
))
s = op.map("fmt", s, lambda kv: (kv[0], f"{{kv[0]}}={{kv[1]:.3f}}"))
op.output("out", s, FileSink({out_path!r}))
'''


def _seq_oracle(cap):
    want = []
    for part in ("p0", "p1"):
        emas = {}
        for i in range(1, cap + 1):
            key = f"{part}-{i % 8}"
            v = float(i % 13)
            prev = emas.get(key)
            emas[key] = v if prev is None else prev + 0.3 * (v - prev)
            want.append(f"{key}={emas[key]:.3f}")
    return sorted(want)


def _child_env(cap, delay_ms):
    return {
        "PYTHONPATH": "/root/repo"
        + os.pathsep
        + os.environ.get("PYTHONPATH", ""),
        "BYTEWAX_TPU_PLATFORM": "cpu",
        "BYTEWAX_TPU_ACCEL": "0",  # keep subprocess startup light
        "SUPERVISE_CAP": str(cap),
        "SUPERVISE_DELAY_MS": str(delay_ms),
    }


def _make_sup(
    tmp_path,
    monkeypatch,
    *,
    name,
    cap,
    delay_ms,
    min_procs,
    max_procs,
    procs,
    hint_fn,
    extra_env=None,
):
    flow_py = tmp_path / f"{name}.py"
    out = tmp_path / f"{name}_out.txt"
    flow_py.write_text(_SEQ_FLOW.format(out_path=str(out)))
    db = tmp_path / f"{name}_db"
    db.mkdir()
    subprocess.run(
        [sys.executable, "-m", "bytewax_tpu.recovery", str(db), "2"],
        env={**os.environ, **_child_env(cap, delay_ms)},
        check=True,
        timeout=60,
    )
    monkeypatch.setenv("BYTEWAX_TPU_AUTOSCALE_POLL_S", "0.2")
    monkeypatch.setenv("BYTEWAX_TPU_AUTOSCALE_HYSTERESIS", "2")
    monkeypatch.setenv("BYTEWAX_TPU_AUTOSCALE_COOLDOWN_S", "0")
    monkeypatch.setenv("BYTEWAX_TPU_AUTOSCALE_STOP_TIMEOUT_S", "60")
    sup = ClusterSupervisor(
        f"{flow_py}:flow",
        min_procs=min_procs,
        max_procs=max_procs,
        procs=procs,
        recovery_dir=str(db),
        snapshot_interval_s=0,
        backup_interval_s=0,
        env={**_child_env(cap, delay_ms), **(extra_env or {})},
        hint_fn=hint_fn,
        log_dir=str(tmp_path / f"{name}_logs"),
        workdir=str(tmp_path),
    )
    return sup, out


def _child_logs(tmp_path, name):
    return "".join(
        p.read_text(errors="replace")
        for p in sorted(Path(tmp_path).glob(f"{name}_logs/child-*.log"))
    )


@pytest.mark.slow
@pytest.mark.parametrize(
    "p_from,p_to,advice",
    [(2, 3, "grow"), (3, 2, "shrink")],
    ids=["grow-2to3", "shrink-3to2"],
)
def test_autoscale_elasticity_exactly_once(
    tmp_path, monkeypatch, p_from, p_to, advice
):
    # A running stateful cluster receives a grow (resp. shrink)
    # decision for K consecutive polls on the LEGACY restart path
    # (BYTEWAX_TPU_AUTOSCALE_LIVE=0): the supervisor gracefully
    # drains it (stop vote on the epoch-close round, snapshots
    # committed), relaunches at the new size with the startup
    # migration, and the completed run's output equals the host
    # oracle exactly-once.
    monkeypatch.setenv("BYTEWAX_TPU_AUTOSCALE_LIVE", "0")
    name = f"auto_{p_from}to{p_to}"
    cap = 500
    sup, out = _make_sup(
        tmp_path,
        monkeypatch,
        name=name,
        cap=cap,
        delay_ms=8,
        min_procs=min(p_from, p_to),
        max_procs=max(p_from, p_to),
        procs=p_from,
        hint_fn=lambda: advice,
    )
    with sup:
        rc = sup.run()
    logs = _child_logs(tmp_path, name)
    assert rc == 0, logs[-3000:]
    assert (advice, p_from, p_to) in sup.actions
    assert sup.current == p_to
    # The move really was the graceful path + startup migration, not
    # a crash-and-replay: the children logged the rescale, and no
    # hard relaunch action fired.
    assert "rescaled recovery store" in logs, logs[-3000:]
    assert all(a[0] != "relaunch" for a in sup.actions)
    assert sorted(out.read_text().split()) == _seq_oracle(cap), (
        f"output diverged from oracle across the {p_from}->{p_to} move"
    )


@pytest.mark.slow
@pytest.mark.parametrize(
    "p_from,p_to,advice",
    [(2, 3, "grow"), (3, 2, "shrink")],
    ids=["grow-2to3", "shrink-3to2"],
)
def test_live_rescale_moves_without_bouncing_survivors(
    tmp_path, monkeypatch, p_from, p_to, advice
):
    # The live partial-rescale path (the default; docs/recovery.md
    # "Live partial rescale"): the membership change rides an epoch
    # close — the joiner boots while the cluster keeps serving, the
    # survivors re-enter run startup IN-PROCESS (same pids before and
    # after), the retiree exits cleanly after the agreed close, and
    # the completed run's output equals the host oracle exactly-once
    # in both directions.  Non-moving workers must close at least one
    # epoch DURING the move (the supervisor samples a survivor's
    # epoch before spawning/posting and after completion).
    name = f"live_{p_from}to{p_to}"
    cap = 500
    sup, out = _make_sup(
        tmp_path,
        monkeypatch,
        name=name,
        cap=cap,
        delay_ms=8,
        min_procs=min(p_from, p_to),
        max_procs=max(p_from, p_to),
        procs=p_from,
        hint_fn=lambda: advice,
    )
    with sup:
        rc = sup.run()
    logs = _child_logs(tmp_path, name)
    assert rc == 0, logs[-3000:]
    assert (advice, p_from, p_to) in sup.actions
    assert sup.current == p_to
    move = sup.last_live_move
    assert move is not None, (
        "the move fell back to the restart path:\n" + logs[-3000:]
    )
    # Survivors were never bounced: every pre-move pid that survived
    # the resize is still the same OS process afterwards.
    surviving = min(p_from, p_to)
    assert (
        move["pids_after"][:surviving]
        == move["pids_before"][:surviving]
    )
    # The non-moving workers kept closing epochs during the move:
    # the agreed reconfiguration itself rides an epoch close, so the
    # survivor's epoch strictly advances between the two samples.
    assert move["epoch_before"] is not None
    assert move["epoch_after"] is not None
    assert move["epoch_after"] > move["epoch_before"], move
    # In-process re-entry, not a relaunch — and the delta migration
    # ran (the rescale log line comes from the surviving proc 0 /
    # the rebuilt coordinator, not a fresh process).
    assert "live reconfigure agreed" in logs, logs[-3000:]
    assert "rescaled recovery store" in logs, logs[-3000:]
    assert all(a[0] != "relaunch" for a in sup.actions)
    assert sorted(out.read_text().split()) == _seq_oracle(cap), (
        f"output diverged from oracle across the live "
        f"{p_from}->{p_to} move"
    )


@pytest.mark.slow
def test_live_rescale_crash_mid_partial_migration_exactly_once(
    tmp_path, monkeypatch
):
    # Chaos on the LIVE move, through the real pinned fault site: the
    # coordinator (proc 0, the one process that runs the delta
    # migration) takes an injected CRASH at rescale_migrate inside
    # the store transaction during its in-process re-entry.  The
    # rolled-back migration retries under the in-process supervisor
    # WITH the agreed membership (proc 0 never leaves the process);
    # the NON-coordinator peers — blocked in the post-"fcfg" gsync
    # wait behind the migration — observe the torn mesh, restart
    # in-process against the new address list, and the re-formed
    # cluster completes the move: output equals the host oracle
    # exactly-once.
    name = "live_crash"
    cap = 500
    sup, out = _make_sup(
        tmp_path,
        monkeypatch,
        name=name,
        cap=cap,
        delay_ms=8,
        min_procs=2,
        max_procs=3,
        procs=2,
        hint_fn=lambda: "grow",
        extra_env={
            "BYTEWAX_TPU_FAULTS": "rescale_migrate:crash:*:0:x1",
            "BYTEWAX_TPU_MAX_RESTARTS": "3",
            "BYTEWAX_TPU_RESTART_BACKOFF_S": "0.1",
        },
    )
    with sup:
        rc = sup.run()
    logs = _child_logs(tmp_path, name)
    assert rc == 0, logs[-3000:]
    assert ("grow", 2, 3) in sup.actions
    # The crash really fired mid-move and was healed by the
    # in-process supervisor — not by the outer relaunch path.
    assert "supervised restart" in logs, logs[-3000:]
    assert "rescaled recovery store" in logs, logs[-3000:]
    assert all(a[0] != "relaunch" for a in sup.actions)
    assert sorted(out.read_text().split()) == _seq_oracle(cap), (
        "output diverged from oracle across the crash-mid-migration "
        "live move"
    )


@pytest.mark.slow
def test_supervisor_relaunches_sigkilled_child_exactly_once(
    tmp_path, monkeypatch
):
    # Chaos: SIGKILL one child mid-epoch (a real OS fault through no
    # engine seam).  The outer supervisor relaunches it; the peer
    # observes the socket close and restarts under its in-process
    # supervisor; the re-formed cluster resumes from the last
    # committed epoch and the final output is exactly-once.
    name = "sigkill"
    cap = 500
    sup, out = _make_sup(
        tmp_path,
        monkeypatch,
        name=name,
        cap=cap,
        delay_ms=8,
        min_procs=2,
        max_procs=2,
        procs=2,
        hint_fn=lambda: "hold",
    )
    results = []
    with sup:
        thread = threading.Thread(
            target=lambda: results.append(sup.run()), daemon=True
        )
        thread.start()
        # Wait for real progress (output flowing => mid-epoch, both
        # children up), then kill one child outright.
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if (
                out.exists()
                and len(out.read_text().split()) > 20
                and len(sup.children) == 2
                and all(p.poll() is None for p in sup.children)
            ):
                break
            time.sleep(0.05)
        else:
            pytest.fail("cluster never made progress")
        os.kill(sup.children[1].pid, signal.SIGKILL)
        thread.join(timeout=180)
        assert not thread.is_alive(), "supervisor wedged after SIGKILL"
    logs = _child_logs(tmp_path, name)
    assert results == [0], logs[-3000:]
    assert ("relaunch", 2, 2) in sup.actions
    assert sorted(out.read_text().split()) == _seq_oracle(cap), (
        "output diverged from oracle across the SIGKILL + relaunch"
    )
