"""Elastic rescale-on-resume (tentpole of the rescale PR).

A recovery store written by N total workers resumes at M != N only
through the explicit rescale pass (``--rescale`` /
``BYTEWAX_TPU_RESCALE=1``), which re-routes every keyed snapshot row
to the new M-worker modulus at run startup — the one globally-ordered
re-entry point.  Without the opt-in, the typed
``WorkerCountMismatchError`` refuses instead of routing rows with a
stale modulus.  Faults are injected ONLY through the engine's own
injector (the pinned ``rescale_migrate`` site — no monkeypatching of
engine internals).
"""

import os
import pickle
import random
import sqlite3
import subprocess
import sys
from datetime import timedelta
from pathlib import Path

import pytest

import bytewax_tpu.operators as op
from bytewax_tpu import xla
from bytewax_tpu.dataflow import Dataflow
from bytewax_tpu.engine import faults, flight
from bytewax_tpu.engine.driver import (
    _backoff_delay,
    cluster_main,
    derive_rescale_hint,
    run_main,
)
from bytewax_tpu.engine.recovery_store import (
    RecoveryStore,
    WorkerCountMismatchError,
    init_db_dir,
    rescale_snaps_rows,
    route_of,
)
from bytewax_tpu.engine.residency import SpillStore
from bytewax_tpu.recovery import RecoveryConfig
from bytewax_tpu.testing import TestingSink, TestingSource

ZERO_TD = timedelta(seconds=0)


@pytest.fixture(autouse=True)
def _fresh_fault_plan():
    faults.reset()
    yield
    faults.reset()


# -- store-level: the mismatch gate ------------------------------------


def _seed_store(tmp_path, worker_count, keys=("a", "b", "c")):
    init_db_dir(tmp_path, 2)
    store = RecoveryStore(tmp_path)
    store.write_ex_started(0, worker_count, 1)
    store.write_epoch(
        0,
        worker_count,
        1,
        [("df.s", k, pickle.dumps(ord(k[0]))) for k in keys],
        None,
    )
    return store


def test_resume_from_worker_count_gate(tmp_path):
    store = _seed_store(tmp_path, worker_count=2)
    # Equal counts and the legacy no-count call are untouched.
    assert store.resume_from(worker_count=2).stored_worker_counts == (2,)
    assert store.resume_from().resume_epoch == 2
    # A mismatch without the opt-in refuses with the typed error,
    # naming stored vs. actual and how to enable rescale.
    with pytest.raises(
        WorkerCountMismatchError,
        match=r"2 worker\(s\).*has 5.*--rescale.*BYTEWAX_TPU_RESCALE=1",
    ) as exc_info:
        store.resume_from(worker_count=5)
    assert exc_info.value.stored_counts == (2,)
    assert exc_info.value.actual_count == 5
    # With the opt-in, the stored counts ride back for the migration.
    resume = store.resume_from(worker_count=5, allow_rescale=True)
    assert resume.stored_worker_counts == (2,)
    assert (resume.ex_num, resume.resume_epoch) == (1, 2)
    store.close()


def test_rescale_rewrites_routes_and_exs_provenance(tmp_path):
    keys = [f"k{i:02d}" for i in range(40)]
    store = _seed_store(tmp_path, worker_count=2, keys=keys)
    migrated = store.rescale(3, ex_num=0)
    assert migrated == len(keys)
    for part in sorted(Path(tmp_path).glob("part-*.sqlite3")):
        con = sqlite3.connect(part)
        for key, route in con.execute(
            "SELECT state_key, route FROM snaps"
        ):
            assert route == route_of(key, 3)
        for (count,) in con.execute("SELECT worker_count FROM exs"):
            assert count == 3
        con.close()
    # The provenance makes the migration durable: the store now
    # resumes at 3 workers without rescale, and refuses at 2.
    assert store.resume_from(worker_count=3).stored_worker_counts == (3,)
    with pytest.raises(WorkerCountMismatchError):
        store.resume_from(worker_count=2)
    store.close()


def test_rescale_route_scoped_reads_partition_the_state(tmp_path):
    # After migration to M workers, the per-lane route filters return
    # a disjoint cover of the keyed state — each resuming process
    # reads exactly its own keys.
    keys = [f"user-{i}" for i in range(64)]
    store = _seed_store(tmp_path, worker_count=2, keys=keys)
    store.rescale(3, ex_num=0)
    by_lane = {
        w: {k for _s, k, _b in store.iter_snaps(2, routes=[w])}
        for w in range(3)
    }
    assert set().union(*by_lane.values()) == set(keys)
    for w in range(3):
        assert by_lane[w] == {k for k in keys if route_of(k, 3) == w}
        for other in range(w + 1, 3):
            assert not (by_lane[w] & by_lane[other])
    store.close()


def test_rescale_mid_migration_crash_rolls_back_whole(
    tmp_path, monkeypatch
):
    # The pinned fault site fires inside the all-partition transaction
    # before any row moves: an injected crash leaves the store exactly
    # as it was (old routes, old exs provenance), and the retry —
    # what the supervisor does after re-entering run startup —
    # migrates cleanly.
    keys = [f"k{i:02d}" for i in range(10)]
    store = _seed_store(tmp_path, worker_count=2, keys=keys)
    monkeypatch.setenv(
        "BYTEWAX_TPU_FAULTS", "rescale_migrate:crash:*:x1"
    )
    faults.configure(0)
    with pytest.raises(faults.InjectedCrash):
        store.rescale(3, ex_num=0)
    for part in sorted(Path(tmp_path).glob("part-*.sqlite3")):
        con = sqlite3.connect(part)
        for key, route in con.execute(
            "SELECT state_key, route FROM snaps"
        ):
            assert route == route_of(key, 2), "rollback was not whole"
        for (count,) in con.execute("SELECT worker_count FROM exs"):
            assert count == 2
        con.close()
    # The x1 spec is spent: the retry (same process, same plan — the
    # supervisor's restart semantics) succeeds and is idempotent.
    assert store.rescale(3, ex_num=0) == len(keys)
    assert store.rescale(3, ex_num=0) == len(keys)
    store.close()


# -- delta-only (partial) migration ------------------------------------


def test_rescale_partial_rewrites_only_moved_routes(tmp_path):
    # The live-rescale delta mode: a key whose home lane does not
    # change under the old→new modulus is NEVER touched — proven via
    # sqlite total_changes, not just the returned count.
    keys = [f"k{i:03d}" for i in range(200)]
    moved = [k for k in keys if route_of(k, 2) != route_of(k, 3)]
    unmoved = [k for k in keys if route_of(k, 2) == route_of(k, 3)]
    assert moved and unmoved  # the fixture really has both kinds
    init_db_dir(tmp_path, 1)
    con = sqlite3.connect(tmp_path / "part-0.sqlite3")
    con.executemany(
        "INSERT INTO snaps (step_id, state_key, epoch, ser_change, "
        "route) VALUES ('df.s', ?, 1, x'00', ?)",
        [(k, route_of(k, 2)) for k in keys],
    )
    before = con.total_changes
    assert (
        rescale_snaps_rows(con, 3, page_size=16, partial=True)
        == len(moved)
    )
    # Exactly the moved rows were written; unmoved rows never were.
    assert con.total_changes - before == len(moved)
    for key, route in con.execute(
        "SELECT state_key, route FROM snaps"
    ):
        assert route == route_of(key, 3)
    # Idempotent AND write-free on a store already at the new
    # modulus: the second pass touches nothing at all.
    before = con.total_changes
    assert rescale_snaps_rows(con, 3, page_size=16, partial=True) == 0
    assert con.total_changes == before
    # Full mode on the same store rewrites everything (the legacy
    # count), so the two modes stay interchangeable semantically.
    assert rescale_snaps_rows(con, 3, page_size=16) == len(keys)
    con.close()


def test_rescale_partial_heals_legacy_and_mixed_stamps(tmp_path):
    # Crash-healing: rows whose stamps are legacy (-1) or mixed
    # (a half-committed earlier migration) never compare equal to
    # the new route, so the delta pass always rewrites them — even
    # when the key's home lane did not move.
    keys = [f"u{i:02d}" for i in range(30)]
    init_db_dir(tmp_path, 1)
    con = sqlite3.connect(tmp_path / "part-0.sqlite3")
    for epoch in (1, 2):
        con.executemany(
            "INSERT INTO snaps (step_id, state_key, epoch, "
            "ser_change, route) VALUES ('df.s', ?, ?, x'00', ?)",
            [(k, epoch, route_of(k, 3)) for k in keys],
        )
    stale = keys[:7]
    con.executemany(
        "UPDATE snaps SET route = -1 WHERE state_key = ? AND epoch = 1",
        [(k,) for k in stale[:4]],
    )
    con.executemany(
        "UPDATE snaps SET route = 99 WHERE state_key = ? AND epoch = 2",
        [(k,) for k in stale[4:]],
    )
    # Already at the 3-lane modulus except the stale stamps: the
    # delta pass rewrites exactly those keys.
    assert (
        rescale_snaps_rows(con, 3, page_size=8, partial=True)
        == len(stale)
    )
    for key, route in con.execute(
        "SELECT state_key, route FROM snaps"
    ):
        assert route == route_of(key, 3)
    con.close()


def test_rescale_partial_crash_rolls_back_whole(
    tmp_path, monkeypatch
):
    # The pinned rescale_migrate site on the NEW delta path: an
    # injected crash inside the all-partition transaction leaves the
    # store exactly as it was, and the retry — the supervisor's
    # re-entry semantics — migrates the same delta cleanly.
    keys = [f"k{i:02d}" for i in range(40)]
    moved = [k for k in keys if route_of(k, 2) != route_of(k, 3)]
    store = _seed_store(tmp_path, worker_count=2, keys=keys)
    monkeypatch.setenv(
        "BYTEWAX_TPU_FAULTS", "rescale_migrate:crash:*:x1"
    )
    faults.configure(0)
    with pytest.raises(faults.InjectedCrash):
        store.rescale(3, ex_num=0, partial=True)
    for part in sorted(Path(tmp_path).glob("part-*.sqlite3")):
        con = sqlite3.connect(part)
        for key, route in con.execute(
            "SELECT state_key, route FROM snaps"
        ):
            assert route == route_of(key, 2), "rollback was not whole"
        con.close()
    # The retry migrates exactly the delta; re-running it migrates
    # nothing (and the store is fully at the new modulus).
    assert store.rescale(3, ex_num=0, partial=True) == len(moved)
    assert store.rescale(3, ex_num=0, partial=True) == 0
    assert store.resume_from(worker_count=3).stored_worker_counts == (3,)
    store.close()


# -- row-format pin: recovery partitions and the spill tier ------------


def _table_shape(db_path):
    con = sqlite3.connect(db_path)
    info = [
        (name, ctype, notnull, pk)
        for _cid, name, ctype, notnull, _dflt, pk in con.execute(
            "PRAGMA table_info(snaps)"
        )
    ]
    con.close()
    return info


def test_spill_rows_share_snaps_format_and_migration(tmp_path):
    # The residency spill tier IS recovery-format rows: identical
    # column shape (route included), identical route stamping, and
    # the SAME migration routine applies.
    db = tmp_path / "db"
    db.mkdir()
    store = _seed_store(db, worker_count=2)
    store.close()
    spill = SpillStore(str(tmp_path / "spill"), "df.s", worker_count=2)
    spill.put_many(
        [(f"u{i}", float(i)) for i in range(20)], epoch=1
    )
    part = next(Path(db).glob("part-0.sqlite3"))
    assert _table_shape(part) == _table_shape(spill._path)
    con = sqlite3.connect(spill._path)
    for key, route in con.execute("SELECT state_key, route FROM snaps"):
        assert route == route_of(key, 2)
    con.close()
    # Shared migration routine, via the SpillStore surface.
    assert spill.rescale(5) == 20
    con = sqlite3.connect(spill._path)
    for key, route in con.execute("SELECT state_key, route FROM snaps"):
        assert route == route_of(key, 5)
    con.close()
    # And rescale_snaps_rows works directly on any snaps-format file.
    con = sqlite3.connect(spill._path)
    assert rescale_snaps_rows(con, 7) == 20
    con.close()
    # The delta-only mode rides the same shared routine (the raw
    # pass above was never committed — its connection closed without
    # one — so the store is still at the 5-lane modulus): already-at-
    # target rewrites nothing, a real move rewrites exactly the
    # changed-route keys.
    assert spill.rescale(5, partial=True) == 0
    spill_keys = [f"u{i}" for i in range(20)]
    spill_moved = [
        k for k in spill_keys if route_of(k, 7) != route_of(k, 5)
    ]
    assert spill.rescale(7, partial=True) == len(spill_moved)
    spill.close()


# -- supervisor backoff jitter ----------------------------------------


def test_restart_backoff_jitter_is_seeded_per_proc():
    def delays(proc_id):
        rng = random.Random(f"bytewax-restart:{proc_id}")
        return [_backoff_delay(0.5, a, rng) for a in range(1, 7)]

    # Deterministic per process (reproducible restart schedules)...
    assert delays(0) == delays(0)
    # ...but desynchronized across the cluster: no two processes of a
    # crashed cluster redial on the same schedule (thundering herd).
    assert delays(0) != delays(1) != delays(2)
    # Jitter stays within [0.5x, 1.5x) of the capped exponential
    # curve, so backoff still backs off and still caps.
    for proc in range(4):
        for attempt, d in enumerate(delays(proc), start=1):
            base = min(0.5 * (2 ** (attempt - 1)), 30.0)
            assert 0.5 * base <= d < 1.5 * base


# -- the rescale recommendation signal ---------------------------------


def test_rescale_hint_grow_on_slow_epoch_close():
    advice, reasons = derive_rescale_hint(
        worker_count=2,
        epoch_interval_s=10.0,
        close_p99_s=6.0,
        stall_s_per_close=0.0,
        restores_per_close=0.0,
    )
    assert advice == "grow"
    assert any("epoch_close_p99" in r for r in reasons)


def test_rescale_hint_grow_on_flush_stalls_and_restores():
    advice, reasons = derive_rescale_hint(
        worker_count=1,
        epoch_interval_s=10.0,
        close_p99_s=0.1,
        stall_s_per_close=3.0,
        restores_per_close=0.0,
    )
    assert advice == "grow" and any("stall" in r for r in reasons)
    advice, reasons = derive_rescale_hint(
        worker_count=1,
        epoch_interval_s=0.0,
        close_p99_s=0.001,
        stall_s_per_close=0.0,
        restores_per_close=8.0,
    )
    assert advice == "grow"
    assert any("residency restores" in r for r in reasons)
    # Active two-way disk-tier traffic (spills AND restores) is its
    # own grow reason — the residency-spill-rate signal.
    advice, reasons = derive_rescale_hint(
        worker_count=1,
        epoch_interval_s=10.0,
        close_p99_s=0.1,
        stall_s_per_close=0.0,
        restores_per_close=0.5,
        spill_bytes_per_close=65536.0,
    )
    assert advice == "grow"
    assert any("spill bytes" in r for r in reasons)


def test_rescale_hint_transients_decay_instead_of_latching():
    # Signals are lifetime averages off cumulative counters: a one-off
    # warm-up spill/restore/stall must neither pin "grow" forever nor
    # block "shrink" forever once amortized over many epoch closes.
    advice, _ = derive_rescale_hint(
        worker_count=4,
        epoch_interval_s=10.0,
        close_p99_s=0.1,
        stall_s_per_close=0.001,  # one 1s stall over 1000 closes
        restores_per_close=0.01,  # one restore over 100 closes
        spill_bytes_per_close=10.0,  # one small spill, amortized
    )
    assert advice == "shrink"


def test_rescale_hint_shrink_only_when_everything_quiet():
    quiet = dict(
        epoch_interval_s=10.0,
        close_p99_s=0.1,
        stall_s_per_close=0.0,
        restores_per_close=0.0,
    )
    advice, reasons = derive_rescale_hint(worker_count=4, **quiet)
    assert advice == "shrink" and reasons
    # A single worker can't shrink; any pressure flips to hold.
    assert derive_rescale_hint(worker_count=1, **quiet)[0] == "hold"
    assert (
        derive_rescale_hint(
            worker_count=4, **{**quiet, "restores_per_close": 0.5}
        )[0]
        == "hold"
    )


def test_rescale_hint_hold_before_any_signal():
    advice, reasons = derive_rescale_hint(
        worker_count=2,
        epoch_interval_s=10.0,
        close_p99_s=None,
        stall_s_per_close=0.0,
        restores_per_close=0.0,
    )
    assert (advice, reasons) == ("hold", [])


# -- in-process engine: grow + shrink with the spill tier populated ----


def _ema_flow(inp, out):
    flow = Dataflow("rescale_df")
    s = op.input("inp", flow, TestingSource(inp, batch_size=4))
    scored = op.stateful_map("ema", s, xla.ema(0.3))
    op.output("out", scored, TestingSink(out))
    return flow


def _canon(rows):
    # (key, (orig, ema)) rows; round so device f32 vs host f64
    # arithmetic compares stably (the test_chaos demotion idiom).
    return sorted(
        (k, tuple(round(float(x), 3) for x in v)) for k, v in rows
    )


def _entry(worker_count):
    if worker_count == 1:
        return run_main
    return lambda *a, **kw: cluster_main(
        *a, [], 0, worker_count_per_proc=worker_count, **kw
    )


@pytest.mark.parametrize(
    "n_from,n_to",
    [(1, 3), (3, 1), (2, 3), (3, 2)],
    ids=["grow-1to3", "shrink-3to1", "grow-2to3", "shrink-3to2"],
)
def test_rescale_resume_with_spilled_keys(
    tmp_path, monkeypatch, n_from, n_to
):
    # A run stopped at N total workers resumes at M != N (grow AND
    # shrink, covering the run_main and in-process cluster_main entry
    # points) with the residency budget so small that most keys sit
    # in the host/disk spill tiers when the stop happens — outputs
    # must equal an uninterrupted host-tier oracle.
    n_keys, n_rows = 32, 256
    inp = [
        (f"u{i % n_keys:02d}", float(i % 11)) for i in range(n_rows)
    ]
    half = n_rows // 2
    db = tmp_path / "db"
    db.mkdir()
    init_db_dir(db, 2)
    rc = RecoveryConfig(str(db))
    monkeypatch.setenv("BYTEWAX_TPU_RESCALE", "1")
    monkeypatch.setenv("BYTEWAX_TPU_STATE_BUDGET", "2")
    monkeypatch.setenv("BYTEWAX_TPU_HOST_STATE_BUDGET", "4")
    monkeypatch.setenv(
        "BYTEWAX_TPU_SPILL_DIR", str(tmp_path / "spill")
    )

    spilled_before = flight.RECORDER.counters.get(
        "state_spill_bytes", 0
    )
    out = []
    _entry(n_from)(
        _ema_flow(
            inp[:half] + [TestingSource.EOF()] + inp[half:], out
        ),
        epoch_interval=ZERO_TD,
        recovery_config=rc,
    )
    assert _canon(out) == _canon(_host_ema_oracle(inp[:half]))
    # The stop really left keys in the spill tier (the rescale must
    # carry them: their epoch snapshots read through the manager).
    assert (
        flight.RECORDER.counters.get("state_spill_bytes", 0)
        > spilled_before
    )

    rescales_before = flight.RECORDER.counters.get("rescale_count", 0)
    out2 = []
    _entry(n_to)(
        _ema_flow(
            inp[:half] + [TestingSource.EOF()] + inp[half:], out2
        ),
        epoch_interval=ZERO_TD,
        recovery_config=rc,
    )
    assert (
        flight.RECORDER.counters.get("rescale_count", 0)
        == rescales_before + 1
    )
    assert flight.RECORDER.counters.get("rescale_migrated_keys", 0) > 0
    assert _canon(out2) == _canon(
        _host_ema_oracle(inp)[half:]
    ), f"keyed state lost or duplicated across the {n_from}->{n_to} rescale"


def _host_ema_oracle(rows, alpha=0.3):
    # xla.ema semantics: debiased EMA over (count, s) state.
    state = {}
    out = []
    for key, value in rows:
        count, s = state.get(key, (0, 0.0))
        count += 1
        s = s * (1.0 - alpha) + alpha * value
        state[key] = (count, s)
        ema = s / (1.0 - (1.0 - alpha) ** count)
        out.append((key, (value, ema)))
    return out


# -- live partial rescale: in-process reconfiguration ------------------


@pytest.mark.parametrize(
    "n_from,n_to",
    [(2, 3), (3, 2)],
    ids=["grow-2to3", "shrink-3to2"],
)
def test_live_reconfigure_in_process_exactly_once(
    tmp_path, monkeypatch, n_from, n_to
):
    # A RUNNING flow takes a live reconfigure request mid-stream
    # (docs/recovery.md "Live partial rescale"): the change agrees at
    # the next epoch close, the driver unwinds to the run-startup
    # re-entry IN-PROCESS (one cluster_main call spans both shapes),
    # the startup migration runs delta-only, and the completed output
    # equals the host oracle exactly-once in both directions.
    from bytewax_tpu.engine.driver import request_reconfigure

    n_keys, n_rows = 48, 384
    inp = [
        (f"u{i % n_keys:02d}", float(i % 11)) for i in range(n_rows)
    ]
    half = n_rows // 2
    items = inp[:half] + [("reconf", -1.0)] + inp[half:]
    db = tmp_path / "db"
    db.mkdir()
    init_db_dir(db, 2)
    monkeypatch.setenv("BYTEWAX_FLIGHT_RECORDER", "1")
    flight.RECORDER.activate(True)

    fired = [False]

    def trig(kv):
        if not fired[0] and kv[1] == -1.0:
            fired[0] = True
            request_reconfigure([], workers_per_process=n_to)
        return kv

    out = []
    flow = Dataflow("live_df")
    s = op.input("inp", flow, TestingSource(items, batch_size=4))
    s = op.map("trig", s, trig)
    scored = op.stateful_map("ema", s, xla.ema(0.3))
    op.output("out", scored, TestingSink(out))
    rescales_before = flight.RECORDER.counters.get("rescale_count", 0)
    status = cluster_main(
        flow,
        [],
        0,
        worker_count_per_proc=n_from,
        epoch_interval=ZERO_TD,
        recovery_config=RecoveryConfig(str(db)),
    )
    assert status is None  # ran to EOF at the new size
    assert fired[0]
    # Oracle over the full stream (the trigger sentinel flows through
    # the EMA like any other keyed item).
    assert _canon(out) == _canon(_host_ema_oracle(items)), (
        f"keyed state lost or duplicated across the live "
        f"{n_from}->{n_to} lane move"
    )
    # The move was the in-process re-entry + a DELTA migration, not
    # a full rewrite: strictly fewer keys migrated than the store
    # holds (the unmoved-route keys were skipped).
    assert (
        flight.RECORDER.counters.get("rescale_count", 0)
        == rescales_before + 1
    )
    events = flight.RECORDER.tail(1 << 14)
    resc = [e for e in events if e["kind"] == "rescale"][-1]
    assert resc["to_count"] == n_to
    total_keys = 0
    for part in sorted(Path(db).glob("part-*.sqlite3")):
        con = sqlite3.connect(part)
        total_keys += con.execute(
            "SELECT COUNT(DISTINCT state_key) FROM snaps"
        ).fetchone()[0]
        con.close()
    assert 0 < resc["keys"] < total_keys, (
        f"migrated {resc['keys']} of {total_keys} keys: not a delta"
    )
    assert any(e["kind"] == "reconfigure" for e in events)


def test_live_reconfigure_refused_without_recovery_store(
    monkeypatch,
):
    # A membership change without a recovery store would discard all
    # keyed state and replay the sources: the agreement refuses (and
    # consumes the request) instead of rebuilding into nothing.
    from bytewax_tpu.engine.driver import request_reconfigure

    monkeypatch.setenv("BYTEWAX_FLIGHT_RECORDER", "1")
    flight.RECORDER.activate(True)
    inp = [(f"k{i % 4}", float(i)) for i in range(64)]
    items = inp[:32] + [("reconf", -1.0)] + inp[32:]
    fired = [False]

    def trig(kv):
        if not fired[0] and kv[1] == -1.0:
            fired[0] = True
            request_reconfigure([], workers_per_process=3)
        return kv

    out = []
    flow = Dataflow("live_nostore_df")
    s = op.input("inp", flow, TestingSource(items, batch_size=4))
    s = op.map("trig", s, trig)
    scored = op.stateful_map("ema", s, xla.ema(0.3))
    op.output("out", scored, TestingSink(out))
    reconfs_before = flight.RECORDER.counters.get(
        "reconfigure_count", 0
    )
    status = cluster_main(
        flow,
        [],
        0,
        worker_count_per_proc=2,
        epoch_interval=ZERO_TD,
        recovery_config=None,
    )
    assert status is None and fired[0]
    # No reconfiguration happened; the run completed at 2 lanes with
    # untouched output.
    assert (
        flight.RECORDER.counters.get("reconfigure_count", 0)
        == reconfs_before
    )
    assert _canon(out) == _canon(_host_ema_oracle(items))


def test_live_reconfigure_migration_crash_retries_in_process(
    tmp_path, monkeypatch
):
    # Crash-mid-partial-migration on the LIVE path: the agreed
    # reconfiguration's first in-process re-entry crashes at the
    # pinned rescale_migrate site (inside the store transaction,
    # before any row moves); the in-process supervisor retries the
    # re-entry WITH the agreed target, the rolled-back delta
    # migration re-runs, and the completed output is exactly-once.
    from bytewax_tpu.engine.driver import request_reconfigure

    inp = [(f"k{i % 8}", float(i)) for i in range(96)]
    half = len(inp) // 2
    items = inp[:half] + [("reconf", -1.0)] + inp[half:]
    db = tmp_path / "db"
    db.mkdir()
    init_db_dir(db, 1)
    monkeypatch.setenv(
        "BYTEWAX_TPU_FAULTS", "rescale_migrate:crash:*:x1"
    )
    monkeypatch.setenv("BYTEWAX_TPU_MAX_RESTARTS", "2")
    monkeypatch.setenv("BYTEWAX_TPU_RESTART_BACKOFF_S", "0.05")
    faults.reset()
    monkeypatch.setenv("BYTEWAX_FLIGHT_RECORDER", "1")
    flight.RECORDER.activate(True)

    fired = [False]

    def trig(kv):
        if not fired[0] and kv[1] == -1.0:
            fired[0] = True
            request_reconfigure([], workers_per_process=3)
        return kv

    out = []
    flow = Dataflow("live_crash_df")
    s = op.input("inp", flow, TestingSource(items, batch_size=4))
    s = op.map("trig", s, trig)
    scored = op.stateful_map("ema", s, xla.ema(0.3))
    op.output("out", scored, TestingSink(out))
    restarts_before = flight.RECORDER.counters.get(
        "worker_restart_count", 0
    )
    status = cluster_main(
        flow,
        [],
        0,
        worker_count_per_proc=2,
        epoch_interval=ZERO_TD,
        recovery_config=RecoveryConfig(str(db)),
    )
    assert status is None
    assert (
        flight.RECORDER.counters.get("worker_restart_count", 0)
        == restarts_before + 1
    )
    assert _canon(out) == _canon(_host_ema_oracle(items))


def test_rescale_resume_migration_crash_retries_under_supervisor(
    tmp_path, monkeypatch
):
    # End-to-end through the real fault site IN-PROCESS: the first
    # rescale attempt crashes mid-migration; the supervisor re-enters
    # at run startup, the rolled-back migration re-runs, and the
    # resumed output is exactly-once.
    inp = [(f"k{i % 4}", float(i)) for i in range(64)]
    half = len(inp) // 2
    db = tmp_path / "db"
    db.mkdir()
    init_db_dir(db, 1)
    rc = RecoveryConfig(str(db))
    out = []
    _entry(2)(
        _ema_flow(inp[:half] + [TestingSource.EOF()] + inp[half:], out),
        epoch_interval=ZERO_TD,
        recovery_config=rc,
    )

    monkeypatch.setenv("BYTEWAX_TPU_RESCALE", "1")
    monkeypatch.setenv(
        "BYTEWAX_TPU_FAULTS", "rescale_migrate:crash:*:x1"
    )
    monkeypatch.setenv("BYTEWAX_TPU_MAX_RESTARTS", "2")
    monkeypatch.setenv("BYTEWAX_TPU_RESTART_BACKOFF_S", "0.05")
    faults.reset()
    restarts_before = flight.RECORDER.counters.get(
        "worker_restart_count", 0
    )
    out2 = []
    _entry(3)(
        _ema_flow(inp[:half] + [TestingSource.EOF()] + inp[half:], out2),
        epoch_interval=ZERO_TD,
        recovery_config=rc,
    )
    assert (
        flight.RECORDER.counters.get("worker_restart_count", 0)
        == restarts_before + 1
    )
    assert _canon(out2) == _canon(_host_ema_oracle(inp)[half:])


# -- subprocess clusters: 2<->3 processes under injected crashes -------


def _env(extra=None, accel=False):
    env = dict(os.environ)
    env["PYTHONPATH"] = "/root/repo" + os.pathsep + env.get(
        "PYTHONPATH", ""
    )
    env["BYTEWAX_TPU_PLATFORM"] = "cpu"
    if not accel:
        env["BYTEWAX_TPU_ACCEL"] = "0"  # keep subprocess startup light
    for k in (
        "BYTEWAX_TPU_FAULTS",
        "BYTEWAX_TPU_MAX_RESTARTS",
        "BYTEWAX_TPU_RESCALE",
        "BYTEWAX_TPU_STATE_BUDGET",
        "BYTEWAX_TPU_SPILL_DIR",
        "BYTEWAX_TPU_HOST_STATE_BUDGET",
    ):
        env.pop(k, None)
    if extra:
        env.update(extra)
    return env


_SEQ_FLOW = '''
import os

import bytewax_tpu.operators as op
from bytewax_tpu.dataflow import Dataflow
from bytewax_tpu.connectors.files import FileSink
from bytewax_tpu.inputs import FixedPartitionedSource, StatefulSourcePartition


class _Part(StatefulSourcePartition):
    def __init__(self, name, resume):
        self._name = name
        self._i = resume or 0

    def next_batch(self):
        if self._i >= int(os.environ["RESCALE_CAP"]):
            raise StopIteration()
        self._i += 1
        return [(f"{{self._name}}-{{self._i % 8}}", float(self._i % 13))]

    def snapshot(self):
        return self._i


class SeqSource(FixedPartitionedSource):
    def list_parts(self):
        return ["p0", "p1"]

    def build_part(self, step_id, name, resume):
        return _Part(name, resume)


flow = Dataflow("rescale_cluster_df")
s = op.input("inp", flow, SeqSource())
s = op.stateful_map("ema", s, lambda st, v: (
    (v if st is None else st + 0.3 * (v - st),) * 2
))
s = op.map("fmt", s, lambda kv: (kv[0], f"{{kv[0]}}={{kv[1]:.3f}}"))
op.output("out", s, FileSink({out_path!r}))
'''


def _spawn_cluster(tmp_path, name, procs, cap, db, out_path, extra_env):
    flow_py = tmp_path / f"{name}.py"
    flow_py.write_text(_SEQ_FLOW.format(out_path=str(out_path)))
    env = _env(extra_env)
    env["RESCALE_CAP"] = str(cap)
    cmd = [
        sys.executable,
        "-m",
        "bytewax_tpu.testing",
        f"{flow_py}:flow",
        "-p",
        str(procs),
        "-r",
        str(db),
        "-s",
        "0",
        "-b",
        "0",
    ]
    if extra_env and extra_env.get("BYTEWAX_TPU_RESCALE") == "1":
        cmd.append("--rescale")
    return subprocess.run(
        cmd,
        env=env,
        cwd=tmp_path,
        capture_output=True,
        text=True,
        timeout=240,
    )


def _seq_oracle(cap):
    want = []
    for part in ("p0", "p1"):
        emas = {}
        for i in range(1, cap + 1):
            key = f"{part}-{i % 8}"
            v = float(i % 13)
            prev = emas.get(key)
            emas[key] = (
                v if prev is None else prev + 0.3 * (v - prev)
            )
            want.append(f"{key}={emas[key]:.3f}")
    return sorted(want)


def _init_db(tmp_path, name):
    db = tmp_path / f"{name}_db"
    db.mkdir()
    subprocess.run(
        [sys.executable, "-m", "bytewax_tpu.recovery", str(db), "2"],
        env=_env(),
        check=True,
        timeout=60,
    )
    return db


@pytest.mark.slow
@pytest.mark.parametrize(
    "p_from,p_to", [(2, 3), (3, 2)], ids=["grow-2to3", "shrink-3to2"]
)
def test_cluster_rescale_under_injected_migration_crash(
    tmp_path, p_from, p_to
):
    # A real multi-process cluster stops at N processes (EOF at half
    # the input); the relaunch at M processes takes an injected CRASH
    # at the pinned rescale_migrate site on proc 0 (mid-migration,
    # inside the store transaction).  The supervisors restart the
    # whole cluster, the rolled-back migration re-runs, and the final
    # output is byte-identical to an uninterrupted run — exactly-once
    # across both the resize and the crash.
    name = f"resc_{p_from}to{p_to}"
    cap = 40
    db = _init_db(tmp_path, name)
    out = tmp_path / f"{name}_out.txt"

    res = _spawn_cluster(
        tmp_path, name, p_from, cap // 2, db, out, {}
    )
    assert res.returncode == 0, res.stderr[-3000:]

    res = _spawn_cluster(
        tmp_path,
        name,
        p_to,
        cap,
        db,
        out,
        {
            "BYTEWAX_TPU_RESCALE": "1",
            "BYTEWAX_TPU_FAULTS": "rescale_migrate:crash:*:0:x1",
            "BYTEWAX_TPU_MAX_RESTARTS": "3",
            "BYTEWAX_TPU_RESTART_BACKOFF_S": "0.1",
        },
    )
    assert res.returncode == 0, res.stderr[-3000:]
    assert "supervised restart" in res.stderr, res.stderr[-3000:]
    assert "rescaled recovery store" in res.stderr, res.stderr[-3000:]
    assert sorted(out.read_text().split()) == _seq_oracle(cap)


@pytest.mark.slow
def test_cluster_rescale_refused_without_flag(tmp_path):
    # The same relaunch WITHOUT the opt-in fails fast on every
    # process with the typed mismatch error and consumes nothing.
    name = "refuse"
    cap = 20
    db = _init_db(tmp_path, name)
    out = tmp_path / f"{name}_out.txt"
    res = _spawn_cluster(tmp_path, name, 2, cap // 2, db, out, {})
    assert res.returncode == 0, res.stderr[-3000:]
    before = sorted(out.read_text().split())

    res = _spawn_cluster(tmp_path, name, 3, cap, db, out, {})
    assert res.returncode != 0
    assert "WorkerCountMismatchError" in res.stderr
    assert sorted(out.read_text().split()) == before

    # And with it, the run completes against the oracle.
    res = _spawn_cluster(
        tmp_path, name, 3, cap, db, out, {"BYTEWAX_TPU_RESCALE": "1"}
    )
    assert res.returncode == 0, res.stderr[-3000:]
    assert sorted(out.read_text().split()) == _seq_oracle(cap)
