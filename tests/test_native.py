"""Native C++ IO tests."""

import numpy as np
import pytest

from bytewax_tpu import native


@pytest.fixture(scope="module")
def parser():
    if not native.is_available():
        pytest.skip("native toolchain unavailable")
    return native.BrcParser()


def test_brc_parse(parser):
    ids, temps = parser.parse(b"oslo;-3.5\nrome;18.2\noslo;0.0\n")
    assert temps.tolist() == [-35, 182, 0]
    vocab = parser.vocab()
    assert vocab[ids].tolist() == ["oslo", "rome", "oslo"]


def test_brc_vocab_stable_across_chunks(parser):
    ids1, _ = parser.parse(b"oslo;1.0\n")
    ids2, _ = parser.parse(b"oslo;2.0\n")
    assert ids1[0] == ids2[0]


def test_brc_malformed():
    if not native.is_available():
        pytest.skip("native toolchain unavailable")
    p = native.BrcParser()
    with pytest.raises(ValueError, match="malformed"):
        p.parse(b"oslo;abc\n")


def test_split_point(parser):
    assert parser.split_point(b"a;1.0\nb;2") == 6
    assert parser.split_point(b"no-newline") == 0


def test_brc_file_source_end_to_end(tmp_path):
    if not native.is_available():
        pytest.skip("native toolchain unavailable")
    import bytewax_tpu.operators as op
    from bytewax_tpu import xla
    from bytewax_tpu.dataflow import Dataflow
    from bytewax_tpu.models.brc import BrcFileSource
    from bytewax_tpu.testing import TestingSink, run_main

    path = tmp_path / "measurements.txt"
    rng = np.random.RandomState(0)
    lines = []
    for _ in range(5000):
        station = f"st{rng.randint(20)}"
        temp = rng.randint(-999, 999) / 10
        lines.append(f"{station};{temp:.1f}")
    path.write_text("\n".join(lines) + "\n")

    out = []
    flow = Dataflow("brc_native")
    s = op.input(
        "inp", flow, BrcFileSource(path, part_count=3, chunk_bytes=4096)
    )
    stats = xla.stats_final("stats", s)
    op.output("out", stats, TestingSink(out))
    run_main(flow)

    # Oracle: plain Python aggregation over the same file.
    expect = {}
    for line in lines:
        k, v = line.split(";")
        v = float(v)
        mn, mx, tot, ct = expect.get(k, (float("inf"), float("-inf"), 0.0, 0))
        expect[k] = (min(mn, v), max(mx, v), tot + v, ct + 1)

    got = dict(out)
    assert set(got) == set(expect)
    for k, (mn, mx, tot, ct) in expect.items():
        gmn, gmean, gmx, gct = got[k]
        assert gct == ct, k
        assert abs(gmn - mn) < 1e-4 and abs(gmx - mx) < 1e-4
        assert abs(gmean - tot / ct) < 1e-3


def test_group_kv_fast_path():
    from bytewax_tpu.native import group_kv

    got = group_kv([("a", 1), ("b", 2), ("a", 3)])
    if got is None:
        pytest.skip("no toolchain for the host_ops extension")
    assert got == {"a": [1, 3], "b": [2]}
    # Non-tuple rows and non-str keys must raise so the driver falls
    # back to its permissive Python loop.
    with pytest.raises(TypeError):
        group_kv([("a", 1), ["b", 2]])
    with pytest.raises(TypeError):
        group_kv([(1, "a")])
    # Value identity is preserved (no copying).
    obj = object()
    assert group_kv([("k", obj)])["k"][0] is obj


def test_group_kv_matches_python_loop_in_dataflow(monkeypatch):
    # The host tier with the native grouping produces identical output
    # to a pure-Python run (grouping is forced off via a stub).
    import bytewax_tpu.engine.driver as drv
    import bytewax_tpu.operators as op
    from bytewax_tpu.dataflow import Dataflow
    from bytewax_tpu.testing import TestingSink, TestingSource, run_main

    inp = [(f"k{i % 7}", i) for i in range(500)]

    def build(out):
        flow = Dataflow("native_df")
        s = op.input("inp", flow, TestingSource(inp, batch_size=64))
        s = op.stateful_map(
            "sum", s, lambda st, v: ((st or 0) + v, (st or 0) + v)
        )
        op.output("out", s, TestingSink(out))
        return flow

    fast = []
    run_main(build(fast))
    monkeypatch.setattr(drv, "_native_group_kv", lambda items: None)
    slow = []
    run_main(build(slow))
    assert fast == slow


def test_kv_encode_basic():
    import numpy as np

    from bytewax_tpu.native import kv_encode

    items = [("a", 1), ("b", 2.5), ("a", 3)]
    iddict = {}
    ids = np.empty(3, dtype=np.int32)
    vals = np.empty(3, dtype=np.float64)
    res = kv_encode(items, iddict, ids, vals)
    if res is None:
        import pytest

        pytest.skip("no native toolchain")
    new_keys, all_int = res
    assert new_keys == ["a", "b"]
    assert all_int == 0  # 2.5 is a float
    assert iddict == {"a": 0, "b": 1}
    assert ids.tolist() == [0, 1, 0]
    assert vals.tolist() == [1.0, 2.5, 3.0]
    # Second batch: existing ids reused, only new keys reported.
    items2 = [("b", 4), ("c", 5)]
    ids2 = np.empty(2, dtype=np.int32)
    vals2 = np.empty(2, dtype=np.float64)
    new2, all_int2 = kv_encode(items2, iddict, ids2, vals2)
    assert new2 == ["c"]
    assert all_int2 == 1
    assert ids2.tolist() == [1, 2]


def test_kv_encode_rolls_back_on_error():
    import numpy as np
    import pytest

    from bytewax_tpu.native import kv_encode

    iddict = {"pre": 0}
    items = [("pre", 1), ("new1", 2), ("bad", "not-a-number")]
    ids = np.empty(3, dtype=np.int32)
    vals = np.empty(3, dtype=np.float64)
    try:
        res = kv_encode([], iddict, np.empty(0, np.int32), np.empty(0, np.float64))
    except TypeError:
        res = None
    if res is None:
        pytest.skip("no native toolchain")
    with pytest.raises(TypeError):
        kv_encode(items, iddict, ids, vals)
    # The keys added before the failure are rolled back.
    assert iddict == {"pre": 0}


def test_kv_encode_int64_exact_past_2_53():
    """Exact-int streams keep exact values beyond float64's 2^53
    integer range via the int64 lane (ADVICE r4: the float64
    round-trip silently rounded large counters/timestamps)."""
    import numpy as np

    from bytewax_tpu.native import kv_encode

    big = (1 << 53) + 1  # not representable in float64
    items = [("a", big), ("a", 1), ("b", 7)]
    ids = np.empty(3, dtype=np.int32)
    vals = np.empty(3, dtype=np.float64)
    ivals = np.empty(3, dtype=np.int64)
    res = kv_encode(items, {}, ids, vals, ivals)
    if res is None:
        import pytest

        pytest.skip("native toolchain unavailable")
    _new, all_int = res
    assert all_int
    assert ivals.tolist() == [big, 1, 7]
    assert int(vals[0]) != big  # the float lane rounds; the int lane is why


def test_kv_encode_int_overflow_falls_to_float():
    import numpy as np

    from bytewax_tpu.native import kv_encode

    over = 1 << 70
    items = [("a", over)]
    ids = np.empty(1, dtype=np.int32)
    vals = np.empty(1, dtype=np.float64)
    ivals = np.empty(1, dtype=np.int64)
    res = kv_encode(items, {}, ids, vals, ivals)
    if res is None:
        import pytest

        pytest.skip("native toolchain unavailable")
    _new, all_int = res
    assert not all_int
    assert vals[0] == float(over)
