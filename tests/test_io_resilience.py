"""Connector-edge resilience: transient I/O retry, the dead-letter
queue, and partition quarantine (docs/recovery.md "Connector-edge
resilience").

Faults are injected ONLY through the engine's own injector — the
pinned ``source_poll``/``sink_write`` sites — or raised by real
connector/user code as the typed transient errors; no monkeypatching
of engine internals, so these tests exercise exactly the ladder a
production edge fault would walk: retry → quarantine/exhaustion →
restartable fault → supervised restart, with exactly-once output
checked against fault-free oracles throughout.
"""

import errno
import json
import os
import random
from datetime import timedelta
from types import SimpleNamespace

import pytest

import bytewax_tpu.operators as op
from bytewax_tpu.dataflow import Dataflow
from bytewax_tpu.engine import faults, flight
from bytewax_tpu.engine.backoff import Backoff, backoff_delay, seeded_rng
from bytewax_tpu.engine.dlq import DeadLetterQueue
from bytewax_tpu.errors import (
    TransientSinkError,
    TransientSourceError,
    is_transient_io_error,
)
from bytewax_tpu.inputs import (
    FixedPartitionedSource,
    StatefulSourcePartition,
)
from bytewax_tpu.recovery import RecoveryConfig, init_db_dir
from bytewax_tpu.testing import TestingSink, TestingSource, run_main

ZERO_TD = timedelta(seconds=0)


@pytest.fixture(autouse=True)
def _fresh_fault_plan():
    faults.reset()
    yield
    faults.reset()


def _io_env(monkeypatch, retries=4, backoff="0.005"):
    monkeypatch.setenv("BYTEWAX_TPU_IO_RETRIES", str(retries))
    monkeypatch.setenv("BYTEWAX_TPU_IO_BACKOFF_S", backoff)


# -- the unified backoff helper (engine/backoff.py) ---------------------


def test_backoff_deterministic_per_seed_desynced_per_proc():
    def delays(proc):
        rng = seeded_rng("io", proc)
        return [backoff_delay(0.5, a, rng=rng) for a in range(1, 7)]

    assert delays(0) == delays(0)
    assert delays(0) != delays(1) != delays(2)


def test_backoff_bounds_and_cap():
    rng = seeded_rng("bounds", 0)
    for attempt in range(1, 12):
        curve = min(0.5 * 2 ** (attempt - 1), 30.0)
        d = backoff_delay(0.5, attempt, rng=rng)
        assert 0.5 * curve <= d < 1.5 * curve
    # No jitter: exact capped exponential.
    assert [backoff_delay(1.0, a, cap=4.0) for a in (1, 2, 3, 4)] == [
        1.0,
        2.0,
        4.0,
        4.0,
    ]
    # Unbounded attempt counts (a quarantined partition reprobes
    # forever) must not overflow float — the exponent clamps.
    assert backoff_delay(0.05, 5000, cap=30.0) == 30.0


def test_backoff_supervisor_parity():
    # driver._backoff_delay is the same implementation (unified per
    # the resilience PR): identical draws from identically-seeded
    # streams produce identical delays.
    from bytewax_tpu.engine.driver import _backoff_delay

    a = _backoff_delay(0.5, 3, random.Random("x"))
    b = backoff_delay(0.5, 3, rng=random.Random("x"))
    assert a == b


def test_backoff_ladder_object():
    b = Backoff(0.5, cap=2.0)
    assert [b.next_delay() for _ in range(3)] == [0.5, 1.0, 2.0]
    assert b.failures == 3
    b.reset()
    assert b.failures == 0


# -- transient classification -------------------------------------------


def test_transient_classification():
    assert is_transient_io_error(TransientSourceError("x"))
    assert is_transient_io_error(TransientSinkError("x"))
    assert is_transient_io_error(TimeoutError())
    assert is_transient_io_error(
        OSError(errno.EAGAIN, os.strerror(errno.EAGAIN))
    )
    assert is_transient_io_error(ConnectionResetError(errno.ECONNRESET, "r"))
    assert not is_transient_io_error(OSError(errno.ENOENT, "gone"))
    assert not is_transient_io_error(PermissionError(errno.EACCES, "no"))
    assert not is_transient_io_error(ValueError("bug"))
    # Mesh liveness stays a supervisor concern, never an edge retry.
    from bytewax_tpu.errors import ClusterPeerDead

    assert not is_transient_io_error(ClusterPeerDead("peer", peer=1))


def test_transient_errors_are_restartable():
    from bytewax_tpu.engine.driver import _RESTARTABLE

    assert isinstance(TransientSourceError("x"), _RESTARTABLE)
    assert isinstance(TransientSinkError("x"), _RESTARTABLE)


# -- retry through the real fault sites, all 3 entry points -------------


def test_source_poll_transient_retry_exactly_once(
    entry_point, monkeypatch
):
    monkeypatch.setenv("BYTEWAX_TPU_FAULTS", "source_poll:error:*:x2")
    _io_env(monkeypatch)
    inp = [(f"k{i % 3}", i) for i in range(12)]
    out = []
    flow = Dataflow("io_src_df")
    s = op.input("inp", flow, TestingSource(inp, batch_size=2))
    op.output("out", s, TestingSink(out))
    retries_before = flight.RECORDER.counters.get("io_retries_count", 0)
    restarts_before = flight.RECORDER.counters.get(
        "worker_restart_count", 0
    )
    entry_point(flow, epoch_interval=ZERO_TD)
    assert out == inp
    assert (
        flight.RECORDER.counters.get("io_retries_count", 0)
        >= retries_before + 2
    )
    # Absorbed at the edge: zero supervised restarts.
    assert (
        flight.RECORDER.counters.get("worker_restart_count", 0)
        == restarts_before
    )


def test_sink_write_transient_retry_exactly_once(
    entry_point, monkeypatch
):
    monkeypatch.setenv("BYTEWAX_TPU_FAULTS", "sink_write:error:*:x2")
    _io_env(monkeypatch)
    inp = list(range(10))
    out = []
    flow = Dataflow("io_sink_df")
    s = op.input("inp", flow, TestingSource(inp, batch_size=2))
    op.output("out", s, TestingSink(out))
    entry_point(flow, epoch_interval=ZERO_TD)
    assert out == inp


def test_user_source_transient_oserror_classified(monkeypatch):
    # No injector involved: a source raising a plain transient
    # OSError (EAGAIN) gets the same retry ladder via the default
    # classification.
    _io_env(monkeypatch)

    class FlakyPart(StatefulSourcePartition):
        def __init__(self, resume):
            self._i = resume or 0
            self._flaked = 0

        def next_batch(self):
            if self._i >= 5:
                raise StopIteration()
            if self._i == 2 and self._flaked < 2:
                self._flaked += 1
                raise OSError(errno.EAGAIN, "try again")
            self._i += 1
            return [self._i - 1]

        def snapshot(self):
            return self._i

    class FlakySource(FixedPartitionedSource):
        def list_parts(self):
            return ["p0"]

        def build_part(self, step_id, name, resume):
            return FlakyPart(resume)

    out = []
    flow = Dataflow("flaky_df")
    s = op.input("inp", flow, FlakySource())
    op.output("out", s, TestingSink(out))
    run_main(flow, epoch_interval=ZERO_TD)
    assert out == [0, 1, 2, 3, 4]


# -- escalation: exhaustion -> restartable fault -> supervisor ----------


def test_exhaustion_escalates_restartable(monkeypatch):
    monkeypatch.setenv("BYTEWAX_TPU_FAULTS", "source_poll:error:*")
    _io_env(monkeypatch, retries=1)
    monkeypatch.delenv("BYTEWAX_TPU_MAX_RESTARTS", raising=False)
    flow = Dataflow("esc_df")
    s = op.input("inp", flow, TestingSource([1, 2], batch_size=1))
    op.output("out", s, TestingSink([]))
    with pytest.raises(TransientSourceError, match="exhausted"):
        run_main(flow, epoch_interval=ZERO_TD)


def test_sink_exhaustion_escalates_restartable(monkeypatch):
    monkeypatch.setenv("BYTEWAX_TPU_FAULTS", "sink_write:error:*")
    _io_env(monkeypatch, retries=1)
    flow = Dataflow("esc_sink_df")
    s = op.input("inp", flow, TestingSource([1, 2], batch_size=1))
    op.output("out", s, TestingSink([]))
    with pytest.raises(TransientSinkError, match="exhausted"):
        run_main(flow, epoch_interval=ZERO_TD)


def test_sink_plain_oserror_is_not_retried(monkeypatch):
    # Sink retries are typed-opt-in ONLY: a plain transient-errno
    # OSError from write_batch may have landed half the batch, so
    # re-sending would duplicate rows — it unwinds to the supervisor
    # (truncating-sink replay) instead of the in-place ladder.
    from bytewax_tpu.outputs import DynamicSink, StatelessSinkPartition

    _io_env(monkeypatch)

    class HalfWrittenPart(StatelessSinkPartition):
        def write_batch(self, items):
            raise OSError(errno.ECONNRESET, "reset mid-batch")

    class HalfWrittenSink(DynamicSink):
        def build(self, step_id, worker_index, worker_count):
            return HalfWrittenPart()

    flow = Dataflow("half_sink_df")
    s = op.input("inp", flow, TestingSource([1, 2]))
    op.output("out", s, HalfWrittenSink())
    retries_before = flight.RECORDER.counters.get("io_retries_count", 0)
    with pytest.raises(OSError):
        run_main(flow, epoch_interval=ZERO_TD)
    assert (
        flight.RECORDER.counters.get("io_retries_count", 0)
        == retries_before
    )


def _stateful_file_flow(inp, out_path):
    from bytewax_tpu.connectors.files import FileSink

    flow = Dataflow("io_esc_df")
    s = op.input("inp", flow, TestingSource(inp))
    s = op.stateful_map(
        "sum", s, lambda st, v: ((st or 0) + v, (st or 0) + v)
    )
    s = op.map("fmt", s, lambda kv: (kv[0], f"{kv[0]}={kv[1]}"))
    op.output("out", s, FileSink(out_path))
    return flow


@pytest.mark.parametrize("site", ["source_poll", "sink_write"])
def test_escalation_supervised_restart_exactly_once(
    entry_point, tmp_path, monkeypatch, site
):
    # Past the retry budget the transient fault escalates to the
    # supervisor; the restarted execution resumes from the last
    # committed epoch and output matches the fault-free oracle —
    # whole-cluster restart as the escalation path, not the first
    # response.  (x3 firings, budget 1: the first run burns 2 and
    # escalates, the restarted run burns 1, retries once, completes.)
    monkeypatch.setenv("BYTEWAX_TPU_FAULTS", f"{site}:error:*:x3")
    _io_env(monkeypatch, retries=1)
    monkeypatch.setenv("BYTEWAX_TPU_MAX_RESTARTS", "3")
    monkeypatch.setenv("BYTEWAX_TPU_RESTART_BACKOFF_S", "0.05")
    inp = [(f"k{i % 3}", i) for i in range(12)]
    out_path = tmp_path / "out.txt"
    db = tmp_path / "db"
    db.mkdir()
    init_db_dir(db, 1)
    restarts_before = flight.RECORDER.counters.get(
        "worker_restart_count", 0
    )
    entry_point(
        _stateful_file_flow(inp, str(out_path)),
        epoch_interval=ZERO_TD,
        recovery_config=RecoveryConfig(str(db)),
    )
    assert (
        flight.RECORDER.counters.get("worker_restart_count", 0)
        >= restarts_before + 1
    )
    sums, want = {}, []
    for k, v in inp:
        sums[k] = sums.get(k, 0) + v
        want.append(f"{k}={sums[k]}")
    assert sorted(out_path.read_text().split()) == sorted(want)


# -- dead-letter queue --------------------------------------------------


def test_csv_dlq_poison_row_itemized(tmp_path, monkeypatch):
    path = tmp_path / "rows.csv"
    path.write_bytes(b"name,score\na,1\nbad\x00row,9\nb,2\n")
    monkeypatch.setenv("BYTEWAX_TPU_DLQ_DIR", str(tmp_path / "dlq"))
    from bytewax_tpu.connectors.files import CSVSource

    out = []
    flow = Dataflow("csv_dlq_df")
    s = op.input("inp", flow, CSVSource(str(path), on_error="dlq"))
    op.output("out", s, TestingSink(out))
    run_main(flow, epoch_interval=ZERO_TD)
    assert out == [
        {"name": "a", "score": "1"},
        {"name": "b", "score": "2"},
    ]
    rows = [
        json.loads(line)
        for line in (tmp_path / "dlq" / "dlq-p00.jsonl").read_text().splitlines()
    ]
    assert len(rows) == 1
    rec = rows[0]
    assert rec["step_id"] == "csv_dlq_df.inp"
    assert "NUL" in rec["error"]
    assert "bad" in rec["payload"]
    assert rec["epoch"] >= 1 and rec["part"].endswith("rows.csv")


def test_file_dlq_undecodable_line_columnar(tmp_path, monkeypatch):
    path = tmp_path / "lines.txt"
    path.write_bytes(b"one\n\xff\xfe broken\ntwo\n")
    monkeypatch.setenv("BYTEWAX_TPU_DLQ_DIR", str(tmp_path / "dlq"))
    from bytewax_tpu.connectors.files import FileSource

    out = []
    flow = Dataflow("file_dlq_df")
    s = op.input(
        "inp", flow, FileSource(str(path), columnar=True, on_error="dlq")
    )
    op.output("out", s, TestingSink(out))
    run_main(flow, epoch_interval=ZERO_TD)
    assert out == ["one", "two"]
    rows = [
        json.loads(line)
        for line in (tmp_path / "dlq" / "dlq-p00.jsonl").read_text().splitlines()
    ]
    assert len(rows) == 1
    assert "UnicodeDecodeError" in rows[0]["error"]


def test_file_columnar_strict_mode_still_raises(tmp_path):
    path = tmp_path / "lines.txt"
    path.write_bytes(b"one\n\xff\xfe broken\ntwo\n")
    from bytewax_tpu.connectors.files import FileSource

    flow = Dataflow("file_strict_df")
    s = op.input("inp", flow, FileSource(str(path), columnar=True))
    op.output("out", s, TestingSink([]))
    with pytest.raises(UnicodeDecodeError):
        run_main(flow, epoch_interval=ZERO_TD)


def test_kafka_dlq_error_frames(monkeypatch):
    from bytewax_tpu.connectors.kafka import KafkaSource, inmem

    monkeypatch.setenv("BYTEWAX_TPU_DLQ_DIR", "")
    broker = inmem.broker_for("inmem://dlq-test")
    broker.create_topic("ev", partitions=1)
    broker.produce("ev", key=b"k", value=b"a")
    broker.inject_error("ev", 0, 1, "OFFSET_OUT_OF_RANGE")
    broker.produce("ev", key=b"k", value=b"b")
    dlq_before = flight.RECORDER.counters.get("dlq_records_count", 0)
    out = []
    with inmem.installed():
        flow = Dataflow("kafka_dlq_df")
        s = op.input(
            "inp",
            flow,
            KafkaSource(
                ["inmem://dlq-test"], ["ev"], tail=False, on_error="dlq"
            ),
        )
        op.output("out", s, TestingSink(out))
        run_main(flow, epoch_interval=ZERO_TD)
    assert [m.value for m in out] == [b"a", b"b"]
    assert (
        flight.RECORDER.counters.get("dlq_records_count", 0)
        == dlq_before + 1
    )


def test_kafka_dlq_transient_frames_take_retry_ladder(monkeypatch):
    # Under on_error="dlq", TRANSIENT error frames are NOT dead
    # letters (a down broker would flood the DLQ with unactionable
    # rows): they take the same retry ladder as the raise policy.
    from bytewax_tpu.connectors.kafka import KafkaSource, inmem

    _io_env(monkeypatch)
    broker = inmem.broker_for("inmem://dlq-transient")
    broker.create_topic("ev", partitions=1)
    broker.produce("ev", key=b"k", value=b"a")
    broker.inject_error("ev", 0, -195, "broker transport failure")
    broker.produce("ev", key=b"k", value=b"b")
    dlq_before = flight.RECORDER.counters.get("dlq_records_count", 0)
    retries_before = flight.RECORDER.counters.get("io_retries_count", 0)
    out = []
    with inmem.installed():
        flow = Dataflow("kafka_dlq_t_df")
        s = op.input(
            "inp",
            flow,
            KafkaSource(
                ["inmem://dlq-transient"],
                ["ev"],
                tail=False,
                on_error="dlq",
            ),
        )
        op.output("out", s, TestingSink(out))
        run_main(flow, epoch_interval=ZERO_TD)
    assert [m.value for m in out] == [b"a", b"b"]
    assert (
        flight.RECORDER.counters.get("dlq_records_count", 0)
        == dlq_before
    )
    assert (
        flight.RECORDER.counters.get("io_retries_count", 0)
        > retries_before
    )


class _AbortOnce:
    def __init__(self):
        self.spent = False


class _DlqPart(StatefulSourcePartition):
    """One item per poll; ('poison', x) items dead-letter instead of
    emitting; an _AbortOnce sentinel hard-aborts exactly once."""

    def __init__(self, items, resume):
        self._items = items
        self._i = resume or 0
        self._dead = []

    def next_batch(self):
        from bytewax_tpu.inputs import AbortExecution

        if self._i >= len(self._items):
            raise StopIteration()
        it = self._items[self._i]
        if isinstance(it, _AbortOnce):
            if not it.spent:
                it.spent = True
                raise AbortExecution()
            self._i += 1
            return []
        self._i += 1
        if isinstance(it, tuple) and it[0] == "poison":
            self._dead.append({"error": "poison", "payload": it[1]})
            return []
        return [it]

    def drain_dead_letters(self):
        dead, self._dead = self._dead, []
        return dead

    def snapshot(self):
        return self._i


class _DlqSource(FixedPartitionedSource):
    def __init__(self, items):
        self._items = items

    def list_parts(self):
        return ["p0"]

    def build_part(self, step_id, name, resume):
        return _DlqPart(self._items, resume)


def test_dlq_rows_survive_abort_resume_exactly_once(
    tmp_path, monkeypatch
):
    # The acceptance pairing: DLQ rows land in the epoch whose
    # snapshots cover the consumed offsets, so a hard abort
    # (AbortExecution: no final snapshot) and resume neither drops
    # nor duplicates a dead-lettered row — committed epochs' rows
    # survive, the aborted epoch's are truncated and recaptured by
    # the replay.
    monkeypatch.setenv("BYTEWAX_TPU_DLQ_DIR", str(tmp_path / "dlq"))
    db = tmp_path / "db"
    db.mkdir()
    init_db_dir(db, 1)
    items = [
        1,
        ("poison", "p0"),
        2,
        3,
        ("poison", "p1"),
        _AbortOnce(),
        4,
        ("poison", "p2"),
        5,
    ]
    out = []

    def build():
        flow = Dataflow("dlq_resume_df")
        s = op.input("inp", flow, _DlqSource(items))
        op.output("out", s, TestingSink(out))
        return flow

    run_main(
        build(),
        epoch_interval=ZERO_TD,
        recovery_config=RecoveryConfig(str(db)),
    )
    run_main(
        build(),
        epoch_interval=ZERO_TD,
        recovery_config=RecoveryConfig(str(db)),
    )
    assert out == [1, 2, 3, 4, 5]
    rows = [
        json.loads(line)
        for line in (tmp_path / "dlq" / "dlq-p00.jsonl").read_text().splitlines()
    ]
    assert sorted(r["payload"] for r in rows) == ["p0", "p1", "p2"]
    assert all(r["step_id"] == "dlq_resume_df.inp" for r in rows)


def test_dlq_truncate_for_resume_unit(tmp_path):
    dlq = DeadLetterQueue(0, dlq_dir=str(tmp_path))
    dlq.capture("s", "p", [{"error": "e1", "payload": "a"}], epoch=1)
    dlq.flush()
    dlq.capture("s", "p", [{"error": "e2", "payload": "b"}], epoch=2)
    dlq.flush()
    assert dlq.truncate_for_resume(2) == 1
    rows = [
        json.loads(line)
        for line in (tmp_path / "dlq-p00.jsonl").read_text().splitlines()
    ]
    assert [r["payload"] for r in rows] == ["a"]
    # Idempotent; nothing below the resume point is touched.
    assert dlq.truncate_for_resume(2) == 0


# -- partition quarantine -----------------------------------------------


class _TwoPartSource(FixedPartitionedSource):
    """p_good streams n items; p_bad fails its first ``fail_polls``
    polls with a typed transient error, then streams its items.

    ``good_delay_ms`` paces p_good's emissions via ``next_awake`` so
    its stream deterministically outlasts p_bad's retry/quarantine
    window — without it the assertion "epochs keep closing while
    p_bad is parked" races the microsecond-scale run loop (p_good can
    drain its handful of items before p_bad's first backoff even
    expires)."""

    def __init__(self, n, fail_polls, good_delay_ms=0.0):
        self._n = n
        self._fail_polls = fail_polls
        self._good_delay_ms = good_delay_ms
        self.bad_fails = {"left": fail_polls}

    def list_parts(self):
        return ["p_bad", "p_good"]

    def build_part(self, step_id, name, resume):
        src = self

        class Part(StatefulSourcePartition):
            def __init__(self):
                self._i = resume or 0
                self._awake = None

            def next_batch(self):
                if name == "p_bad" and src.bad_fails["left"] > 0:
                    src.bad_fails["left"] -= 1
                    raise TransientSourceError("edge down")
                if self._i >= src._n:
                    raise StopIteration()
                self._i += 1
                if name == "p_good" and src._good_delay_ms:
                    from datetime import datetime, timezone

                    self._awake = datetime.now(
                        timezone.utc
                    ) + timedelta(milliseconds=src._good_delay_ms)
                return [(name, self._i)]

            def next_awake(self):
                return self._awake

            def snapshot(self):
                return self._i

        return Part()


def test_quarantine_parks_partition_keeps_rest_flowing(monkeypatch):
    # p_bad exhausts the retry budget and is quarantined (parked at
    # offset 0) while p_good keeps streaming and epochs keep closing;
    # the re-probe heals it and every row still arrives.
    monkeypatch.setenv("BYTEWAX_TPU_QUARANTINE", "1")
    _io_env(monkeypatch, retries=1, backoff="0.002")
    monkeypatch.setenv("BYTEWAX_FLIGHT_RECORDER", "1")
    n = 8
    src = _TwoPartSource(n, fail_polls=4, good_delay_ms=3)
    out = []
    flow = Dataflow("quarantine_df")
    s = op.input("inp", flow, src)
    op.output("out", s, TestingSink(out))
    import time as _time

    t0 = _time.time()
    run_main(flow, epoch_interval=ZERO_TD)

    assert sorted(out) == sorted(
        [(p, i) for p in ("p_bad", "p_good") for i in range(1, n + 1)]
    )
    # Only THIS run's events (the ring persists across tests).
    events = [e for e in flight.RECORDER.tail(512) if e["t"] >= t0]
    kinds = [e["kind"] for e in events]
    q_at = kinds.index("quarantine")
    uq_at = kinds.index("unquarantine", q_at)
    assert events[q_at]["part"] == "p_bad"
    # Graceful degradation: the rest of the dataflow kept closing
    # epochs while p_bad was parked.
    assert "epoch_close" in kinds[q_at:uq_at]
    # Gauge back to zero after the heal.
    assert events[uq_at]["step"] == "quarantine_df.inp"
    assert (
        flight.RECORDER.counters.get(
            "quarantined_partitions[quarantine_df.inp]"
        )
        == 0
    )


def test_quarantine_resets_on_runtime_close_and_hands_off_offset(
    tmp_path, monkeypatch
):
    # The live-rescale quarantine fix (docs/recovery.md "Live partial
    # rescale"): a partition still PARKED when its runtime is torn
    # down (graceful stop here; a rescale rebuild walks the same
    # close path) must not leave a phantom
    # bytewax_quarantined_partitions gauge on the old owner — and the
    # next owner resumes it from the store's frozen last-good-offset
    # snapshot instead of re-reading from zero.
    monkeypatch.setenv("BYTEWAX_TPU_QUARANTINE", "1")
    _io_env(monkeypatch, retries=1, backoff="0.002")
    monkeypatch.setenv("BYTEWAX_FLIGHT_RECORDER", "1")
    flight.RECORDER.activate(True)
    from bytewax_tpu.engine import driver as _driver

    db = tmp_path / "db"
    db.mkdir()
    init_db_dir(db, 1)
    rc = RecoveryConfig(str(db))
    n = 8
    # p_bad never heals during run 1: it stays parked at its last
    # good offset (0) while p_good streams out, then a graceful stop
    # drains the run with the partition STILL quarantined.
    src = _TwoPartSource(n, fail_polls=10_000)
    seen = {"count": 0}

    def trig(item):
        seen["count"] += 1
        if seen["count"] == n:
            _driver.request_stop()
        return item

    out = []
    flow = Dataflow("q_reset_df")
    s = op.input("inp", flow, src)
    s = op.map("trig", s, trig)
    op.output("out", s, TestingSink(out))
    status = run_main(flow, epoch_interval=ZERO_TD, recovery_config=rc)
    assert status is not None  # graceful stop, not EOF
    assert sorted(out) == [("p_good", i) for i in range(1, n + 1)]
    # The runtime teardown zeroed the step's quarantine gauge even
    # though the partition never healed — no phantom on the old
    # owner.
    assert (
        flight.RECORDER.counters.get(
            "quarantined_partitions[q_reset_df.inp]"
        )
        == 0
    )
    events = flight.RECORDER.tail(512)
    assert any(e["kind"] == "quarantine" for e in events)

    # Run 2 ("the new owner"): the partition is healthy now and must
    # resume from the FROZEN offset — p_bad emits all its rows
    # exactly once, p_good replays nothing (offset ladder handed
    # over through the store).
    src2 = _TwoPartSource(n, fail_polls=0)
    out2 = []
    flow2 = Dataflow("q_reset_df")
    s2 = op.input("inp", flow2, src2)
    op.output("out", s2, TestingSink(out2))
    status2 = run_main(
        flow2, epoch_interval=ZERO_TD, recovery_config=rc
    )
    assert status2 is None
    assert sorted(out2) == [("p_bad", i) for i in range(1, n + 1)]


def test_file_source_itemized_dlq_refused():
    # on_error="dlq" is a columnar-decode policy on the line sources;
    # silently ignoring it in itemized mode would be worse than
    # refusing it.
    from bytewax_tpu.connectors.files import DirSource, FileSource

    with pytest.raises(ValueError, match="columnar=True"):
        FileSource("/tmp/x.txt", on_error="dlq")
    with pytest.raises(ValueError, match="columnar=True"):
        DirSource("/tmp", on_error="dlq")


def test_quarantined_partition_eof_clears_gauge(monkeypatch):
    # A quarantined partition that EOFs on its re-probe must leave
    # the health map clean: gauge back to zero, unquarantine noted —
    # no phantom parked partition for alerting to chase.
    monkeypatch.setenv("BYTEWAX_TPU_QUARANTINE", "1")
    _io_env(monkeypatch, retries=1, backoff="0.002")
    monkeypatch.setenv("BYTEWAX_FLIGHT_RECORDER", "1")

    class DrainedPart(StatefulSourcePartition):
        def __init__(self, name, resume):
            self._name = name
            self._i = resume or 0
            self._fails = 0

        def next_batch(self):
            if self._name == "p_bad":
                if self._fails < 3:
                    self._fails += 1
                    raise TransientSourceError("down")
                raise StopIteration()  # recovered straight into EOF
            if self._i >= 4:
                raise StopIteration()
            self._i += 1
            return [(self._name, self._i)]

        def snapshot(self):
            return self._i

    class Src(FixedPartitionedSource):
        def list_parts(self):
            return ["p_bad", "p_good"]

        def build_part(self, step_id, name, resume):
            return DrainedPart(name, resume)

    out = []
    flow = Dataflow("q_eof_df")
    s = op.input("inp", flow, Src())
    op.output("out", s, TestingSink(out))
    run_main(flow, epoch_interval=ZERO_TD)
    assert sorted(out) == [("p_good", i) for i in range(1, 5)]
    assert (
        flight.RECORDER.counters.get(
            "quarantined_partitions[q_eof_df.inp]"
        )
        == 0
    )


def test_quarantine_off_escalates_instead(monkeypatch):
    monkeypatch.delenv("BYTEWAX_TPU_QUARANTINE", raising=False)
    _io_env(monkeypatch, retries=1, backoff="0.002")
    src = _TwoPartSource(4, fail_polls=10)
    flow = Dataflow("noq_df")
    s = op.input("inp", flow, src)
    op.output("out", s, TestingSink([]))
    with pytest.raises(TransientSourceError, match="exhausted"):
        run_main(flow, epoch_interval=ZERO_TD)


def test_source_health_section():
    import time as _time

    from bytewax_tpu.engine.driver import _InputRt

    rt = _InputRt.__new__(_InputRt)
    rt.op = SimpleNamespace(step_id="s")
    rt.parts = {"a": None, "b": None, "c": None}
    rt._quarantined = {
        "a": {
            "since": _time.monotonic() - 2.0,
            "fails": 7,
            "last_error": "TransientSourceError: down",
        }
    }
    rt._io_fails = {"b": 2}
    rt._last_io_error = {"b": "OSError: flaky"}
    health = rt.source_health()
    assert health["a"]["state"] == "quarantined"
    assert health["a"]["consecutive_failures"] == 7
    assert health["a"]["parked_s"] >= 1.9
    assert health["b"] == {
        "state": "retrying",
        "consecutive_failures": 2,
        "last_error": "OSError: flaky",
    }
    assert health["c"] == {"state": "ok"}


def test_status_exposes_source_health_and_dlq(monkeypatch):
    # /status carries the per-partition source-health section and the
    # DLQ summary (served mid-run by the API thread; here read off
    # the driver's own payload builder at quiesce).
    from bytewax_tpu.engine import driver as drv

    seen = {}
    orig = drv._Driver._close_epoch

    def spy(self, workers=None):
        # First close only: by the final (EOF) close the drained
        # partition has left the health map.
        seen.setdefault("status", self._status())
        return orig(self, workers)

    monkeypatch.setattr(drv._Driver, "_close_epoch", spy)
    flow = Dataflow("status_df")
    s = op.input("inp", flow, TestingSource([1, 2, 3]))
    op.output("out", s, TestingSink([]))
    run_main(flow, epoch_interval=ZERO_TD)
    status = seen["status"]
    assert status["source_health"] == {
        "status_df.inp": {"iterable": {"state": "ok"}}
    }
    assert set(status["dlq"]) == {"dir", "captured", "pending_flush"}


# -- chaos soak plumbing ------------------------------------------------


def test_random_soak_site_filter(monkeypatch):
    monkeypatch.setenv("BYTEWAX_TPU_FAULTS", "random")
    monkeypatch.setenv("BYTEWAX_TPU_FAULTS_SITES", "source_poll")
    monkeypatch.setenv("BYTEWAX_TPU_FAULTS_KINDS", "error")
    monkeypatch.setenv("BYTEWAX_TPU_FAULTS_RATE", "1.0")
    monkeypatch.setenv("BYTEWAX_TPU_FAULTS_MIN_GAP_S", "0")
    faults.reset()
    faults.configure(0)
    # Filtered-out sites never fire...
    assert faults.fire("comm.send") is None
    assert faults.fire("barrier") is None
    # ...the selected connector-edge site raises its typed error.
    with pytest.raises(TransientSourceError):
        faults.fire("source_poll", step="s", part="p")


def test_random_soak_unknown_site_rejected(monkeypatch):
    monkeypatch.setenv("BYTEWAX_TPU_FAULTS", "random")
    monkeypatch.setenv("BYTEWAX_TPU_FAULTS_SITES", "nope")
    faults.reset()
    with pytest.raises(ValueError, match="unknown fault site"):
        faults.configure(0)


def test_metric_families_registered(monkeypatch):
    monkeypatch.setenv("BYTEWAX_TPU_FAULTS", "source_poll:error:*:x1")
    _io_env(monkeypatch)
    flow = Dataflow("fam_df")
    s = op.input("inp", flow, TestingSource([1, 2]))
    op.output("out", s, TestingSink([]))
    run_main(flow, epoch_interval=ZERO_TD)
    from bytewax_tpu._metrics import generate_python_metrics

    text = generate_python_metrics()
    assert "bytewax_io_retries_count" in text
    assert "bytewax_dlq_records_count" in text
    assert "bytewax_quarantined_partitions" in text


# -- kafka classification ----------------------------------------------


def test_kafka_transient_code_classification():
    from bytewax_tpu.connectors.kafka import (
        TRANSIENT_KAFKA_CODES,
        inmem,
        is_transient_kafka_error,
    )

    assert is_transient_kafka_error(inmem.KafkaError(-195, "transport"))
    assert is_transient_kafka_error(inmem.KafkaError(7, "req timeout"))
    assert not is_transient_kafka_error(inmem.KafkaError(1, "offset oor"))
    assert not is_transient_kafka_error(None)
    assert -195 in TRANSIENT_KAFKA_CODES

    class Retriable:
        def retriable(self):
            return True

        def code(self):
            return 999

    assert is_transient_kafka_error(Retriable())


def test_kafka_transient_poll_error_retried_in_place(monkeypatch):
    # A transport hiccup mid-log: the typed transient error reaches
    # the engine at a poll boundary, the retry re-polls, and every
    # message lands exactly once with zero restarts — messages the
    # consumer handed over after the error frame included.
    from bytewax_tpu.connectors.kafka import KafkaSource, inmem

    _io_env(monkeypatch)
    broker = inmem.broker_for("inmem://transient-test")
    broker.create_topic("ev", partitions=1)
    for i in range(3):
        broker.produce("ev", key=b"k", value=str(i).encode())
    broker.inject_error("ev", 0, -195, "broker transport failure")
    for i in range(3, 6):
        broker.produce("ev", key=b"k", value=str(i).encode())

    restarts_before = flight.RECORDER.counters.get(
        "worker_restart_count", 0
    )
    out = []
    with inmem.installed():
        flow = Dataflow("kafka_transient_df")
        s = op.input(
            "inp",
            flow,
            KafkaSource(
                ["inmem://transient-test"],
                ["ev"],
                tail=False,
                batch_size=100,
            ),
        )
        op.output("out", s, TestingSink(out))
        run_main(flow, epoch_interval=ZERO_TD)
    assert [m.value for m in out] == [
        str(i).encode() for i in range(6)
    ]
    assert (
        flight.RECORDER.counters.get("worker_restart_count", 0)
        == restarts_before
    )


def test_kafka_partition_quarantine_keeps_others_flowing(monkeypatch):
    # The acceptance shape: one Kafka partition's broker path stays
    # down past the retry budget and is quarantined; the topic's
    # OTHER partition keeps streaming (epochs keep closing) until the
    # sick one heals and every message still lands exactly once.
    from bytewax_tpu.connectors.kafka import KafkaSource, inmem

    monkeypatch.setenv("BYTEWAX_TPU_QUARANTINE", "1")
    _io_env(monkeypatch, retries=1, backoff="0.002")
    monkeypatch.setenv("BYTEWAX_FLIGHT_RECORDER", "1")
    broker = inmem.broker_for("inmem://quarantine-test")
    broker.create_topic("ev", partitions=2)
    # Partition 0: a run of consecutive transport failures (each
    # empty-handed poll raises, climbing the ladder past the budget)
    # then its data; partition 1: clean data throughout.
    for _ in range(4):
        broker.inject_error("ev", 0, -195, "broker transport failure")
    for i in range(4):
        broker.produce("ev", value=f"p0-{i}".encode(), partition=0)
    for i in range(8):
        broker.produce("ev", value=f"p1-{i}".encode(), partition=1)

    out = []
    import time as _time

    t0 = _time.time()
    with inmem.installed():
        flow = Dataflow("kafka_q_df")
        s = op.input(
            "inp",
            flow,
            KafkaSource(
                ["inmem://quarantine-test"],
                ["ev"],
                tail=False,
                batch_size=1,
            ),
        )
        op.output("out", s, TestingSink(out))
        run_main(flow, epoch_interval=ZERO_TD)
    vals = [m.value for m in out]
    assert sorted(vals) == sorted(
        [f"p0-{i}".encode() for i in range(4)]
        + [f"p1-{i}".encode() for i in range(8)]
    )
    # Only THIS run's events (the ring persists across tests).
    events = [e for e in flight.RECORDER.tail(512) if e["t"] >= t0]
    kinds = [e["kind"] for e in events]
    q_at = kinds.index("quarantine")
    uq_at = kinds.index("unquarantine", q_at)
    assert events[q_at]["part"].startswith("0-ev")
    # The healthy partition kept the dataflow moving while partition
    # 0 was parked.
    assert "epoch_close" in kinds[q_at:uq_at]


def test_kafka_nontransient_error_still_raises():
    from bytewax_tpu.connectors.kafka import KafkaSource, inmem

    broker = inmem.broker_for("inmem://fatal-test")
    broker.create_topic("ev", partitions=1)
    broker.produce("ev", key=b"k", value=b"a")
    broker.inject_error("ev", 0, 1, "OFFSET_OUT_OF_RANGE")
    with inmem.installed():
        flow = Dataflow("kafka_fatal_df")
        s = op.input(
            "inp",
            flow,
            KafkaSource(["inmem://fatal-test"], ["ev"], tail=False),
        )
        op.output("out", s, TestingSink([]))
        with pytest.raises(RuntimeError, match="error consuming"):
            run_main(flow, epoch_interval=ZERO_TD)


# -- 2-proc soak over the connector-edge sites (slow) -------------------

_SOAK_FLOW = '''
import os

import bytewax_tpu.operators as op
from bytewax_tpu.dataflow import Dataflow
from bytewax_tpu.connectors.files import FileSink
from bytewax_tpu.inputs import FixedPartitionedSource, StatefulSourcePartition


class _Part(StatefulSourcePartition):
    def __init__(self, name, resume):
        self._name = name
        self._i = resume or 0

    def next_batch(self):
        if self._i >= int(os.environ["SOAK_CAP"]):
            raise StopIteration()
        self._i += 1
        return [(f"{{self._name}}-{{self._i % 4}}", self._i)]

    def snapshot(self):
        return self._i


class SeqSource(FixedPartitionedSource):
    def list_parts(self):
        return ["p0", "p1"]

    def build_part(self, step_id, name, resume):
        return _Part(name, resume)


flow = Dataflow("io_soak_df")
s = op.input("inp", flow, SeqSource())
s = op.stateful_map("sum", s, lambda st, v: ((st or 0) + v, (st or 0) + v))
s = op.map("fmt", s, lambda kv: (kv[0], f"{{kv[0]}}={{kv[1]}}"))
op.output("out", s, FileSink({out_path!r}))
'''


@pytest.mark.slow
def test_cluster_io_fault_soak_zero_restarts(tmp_path):
    # Random seeded transient faults on ONLY the connector-edge sites
    # across a 2-process stateful cluster: every fault is absorbed by
    # the in-place retry ladder — zero supervised restarts — and the
    # output is byte-equal to the fault-free oracle.
    import subprocess
    import sys

    cap = 200
    flow_py = tmp_path / "soak.py"
    out_path = str(tmp_path / "soak_out.txt")
    flow_py.write_text(_SOAK_FLOW.format(out_path=out_path))
    db = tmp_path / "db"
    db.mkdir()
    env = dict(os.environ)
    env["PYTHONPATH"] = "/root/repo" + os.pathsep + env.get(
        "PYTHONPATH", ""
    )
    env["BYTEWAX_TPU_PLATFORM"] = "cpu"
    env["BYTEWAX_TPU_ACCEL"] = "0"
    env.pop("BYTEWAX_TPU_FAULTS", None)
    subprocess.run(
        [sys.executable, "-m", "bytewax_tpu.recovery", str(db), "2"],
        env=env,
        check=True,
        timeout=60,
    )
    env.update(
        {
            "SOAK_CAP": str(cap),
            "BYTEWAX_TPU_FAULTS": "random",
            "BYTEWAX_TPU_FAULTS_SEED": "1713",
            "BYTEWAX_TPU_FAULTS_SITES": "source_poll,sink_write",
            "BYTEWAX_TPU_FAULTS_KINDS": "error,delay",
            "BYTEWAX_TPU_FAULTS_RATE": "0.2",
            "BYTEWAX_TPU_FAULTS_MIN_GAP_S": "0.2",
            "BYTEWAX_TPU_FAULT_DELAY_S": "0.01",
            "BYTEWAX_TPU_IO_RETRIES": "8",
            "BYTEWAX_TPU_IO_BACKOFF_S": "0.01",
            "BYTEWAX_TPU_MAX_RESTARTS": "3",
            "BYTEWAX_TPU_RESTART_BACKOFF_S": "0.1",
        }
    )
    res = subprocess.run(
        [
            sys.executable,
            "-m",
            "bytewax_tpu.testing",
            f"{flow_py}:flow",
            "-p",
            "2",
            "-r",
            str(db),
            "-s",
            "0",
            "-b",
            "0",
        ],
        env=env,
        cwd=tmp_path,
        capture_output=True,
        text=True,
        timeout=240,
    )
    assert res.returncode == 0, res.stderr[-3000:]
    assert "supervised restart" not in res.stderr, res.stderr[-3000:]
    want = []
    for part in ("p0", "p1"):
        sums = {}
        for i in range(1, cap + 1):
            key = f"{part}-{i % 4}"
            sums[key] = sums.get(key, 0) + i
            want.append(f"{key}={sums[key]}")
    from pathlib import Path

    assert sorted(Path(out_path).read_text().split()) == sorted(want)
