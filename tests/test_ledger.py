"""Epoch-ledger tests (tentpole of the observability PR): per-epoch
time attribution and its consumers — `/status`, Prometheus, the
Perfetto ``trace_event`` dump, the attribution-backed rescale hint —
plus the satellite surfaces (`/healthz`, `/stacks`, crash
post-mortems).

The ledger is always-on observability data on a global recorder, so
tests that assert per-run records clear the sealed-record buffer
first (never the engine's own state).
"""

import json
import os
import subprocess
import sys
import time
import urllib.error
import urllib.request
from datetime import timedelta

import pytest

import bytewax_tpu.operators as op
from bytewax_tpu.dataflow import Dataflow
from bytewax_tpu.engine import faults, flight
from bytewax_tpu.engine.driver import derive_rescale_hint
from bytewax_tpu.recovery import RecoveryConfig, init_db_dir
from bytewax_tpu.testing import TestingSink, TestingSource, run_main

ZERO_TD = timedelta(seconds=0)

#: Ledger phases measured on the main thread: disjoint exclusive
#: intervals, so their per-epoch sum may never exceed the epoch wall
#: time ("device" runs on the pipeline worker and overlaps).
_MAIN_PHASES_ONLY = lambda phases: {  # noqa: E731
    p: v for p, v in phases.items() if p != "device"
}


def _reset_ledger():
    rec = flight.RECORDER
    rec._ledgers.clear()
    rec.last_ledger = None
    rec._ledger = {}
    rec._ledger_pre_close = None
    rec._epoch_t0 = time.monotonic()


def _phase_sum(phases):
    return sum(
        s for steps in phases.values() for s in steps.values()
    )


# -- phase attribution sums --------------------------------------------


def test_ledger_phase_sums_all_entry_points(entry_point):
    # Every epoch close seals a ledger record whose main-thread
    # phases are disjoint exclusive intervals: per epoch they sum to
    # no more than the epoch wall time, and over a host-work-heavy
    # run they attribute most of it.
    _reset_ledger()
    out = []
    flow = Dataflow("ledger_df")
    s = op.input("inp", flow, TestingSource(list(range(30)), batch_size=6))
    s = op.map("work", s, lambda x: (time.sleep(0.002), x * 2)[1])
    op.output("out", s, TestingSink(out))
    entry_point(flow, epoch_interval=ZERO_TD)
    assert out and len(out) == 30

    records = flight.RECORDER.ledgers()
    assert records, "no ledger records sealed"
    for rec in records:
        assert isinstance(rec["epoch"], int)
        phases = rec["phases"]
        main_sum = _phase_sum(_MAIN_PHASES_ONLY(phases))
        # Disjoint main-thread intervals: sum <= wall (small slack
        # for float rounding / clock granularity).
        assert main_sum <= rec["wall_s"] * 1.05 + 0.002, rec
        # Close-window breakdown tracks the measured close duration.
        close_sum = sum(rec["close"].values())
        assert close_sum <= rec["close_s"] * 1.1 + 0.002, rec
        assert rec["close_s"] <= rec["wall_s"] * 1.05 + 0.002
    # The sleeping mapper dominates: most wall time is attributed
    # (skip the first record — its window includes driver startup).
    tail = records[1:]
    if tail:
        wall = sum(r["wall_s"] for r in tail)
        attributed = sum(
            _phase_sum(_MAIN_PHASES_ONLY(r["phases"])) for r in tail
        )
        assert attributed >= 0.45 * wall, (attributed, wall)
    # The mapper's step shows up under the host phase somewhere.
    hosts = [r["phases"].get("host", {}) for r in records]
    assert any(
        "ledger_df.work.flat_map_batch" in h for h in hosts
    ), hosts


def _windowed_accel_flow(n_rows=200):
    """Columnar event-time count_window exercising the accelerated
    window step (device pipeline: device/readback phases, processing
    lag) with a ``ts`` column (event-time lag)."""
    from datetime import datetime, timezone

    import numpy as np

    import bytewax_tpu.operators.windowing as w
    from bytewax_tpu.engine.arrays import ArrayBatch
    from bytewax_tpu.models.brc import ArrayBatchSource
    from bytewax_tpu.operators.windowing import (
        EventClock,
        TumblingWindower,
    )

    align = datetime(2022, 1, 1, tzinfo=timezone.utc)
    base = np.datetime64(align.replace(tzinfo=None), "us")
    batches = [
        ArrayBatch(
            {
                "key_id": (np.arange(n_rows) % 2).astype(np.int32),
                "ts": base
                + (np.arange(n_rows) // 10).astype("timedelta64[s]"),
            },
            key_vocab=np.array(["0", "1"]),
        )
    ]
    clock = EventClock(
        ts_getter=lambda x: x, wait_for_system_duration=ZERO_TD
    )
    windower = TumblingWindower(
        align_to=align, length=timedelta(seconds=10)
    )
    out = []
    flow = Dataflow("lag_df")
    s = op.input("in", flow, ArrayBatchSource(batches))
    wo = w.count_window("count", s, clock, windower, key=lambda x: x)
    op.output("out", wo.down, TestingSink(out))
    return flow, out


def test_source_lag_and_device_phase(monkeypatch):
    # Source lag accounting: event_time lag sampled at ingest from
    # the batch's ts column, processing lag from the dispatch
    # pipeline's submit->finalize interval; the device fold's wall
    # time lands in the ledger's worker lane.
    from prometheus_client import REGISTRY

    monkeypatch.setenv("BYTEWAX_TPU_ACCEL", "1")
    _reset_ledger()
    flight.RECORDER._lag.clear()
    flow, out = _windowed_accel_flow()
    run_main(flow, epoch_interval=ZERO_TD)
    assert out  # windows closed on device

    lag = flight.RECORDER._lag
    # The 2022 timestamps are years behind wall clock: a big positive
    # event-time lag, sampled at the input step.
    assert lag.get(("lag_df.in", "event_time"), 0.0) > 0.0
    assert any(kind == "processing" for (_s, kind) in lag), lag
    # Prometheus mirrors of both samples.
    assert (
        REGISTRY.get_sample_value(
            "bytewax_source_lag_seconds",
            {"step_id": "lag_df.in", "kind": "event_time"},
        )
        > 0.0
    )
    # Device fold time attributed on the worker lane.
    assert flight.RECORDER.phase_totals.get("device", 0.0) > 0.0
    # And the epoch_phase_seconds family carries it.
    from bytewax_tpu._metrics import generate_python_metrics

    text = generate_python_metrics()
    assert "bytewax_epoch_phase_seconds" in text
    assert "bytewax_source_lag_seconds" in text


def test_event_lag_nat_timestamp_is_skipped(now):
    # A NaT in the ts column must yield no sample (never NaN — a NaN
    # gauge renders /status as invalid JSON cluster-wide).
    import numpy as np

    from bytewax_tpu.engine.arrays import ArrayBatch
    from bytewax_tpu.engine.driver import _batch_event_lag_s

    ts = np.array(["2022-01-01T00:00:00", "NaT"], dtype="datetime64[us]")
    batch = ArrayBatch(
        {"key_id": np.zeros(2, dtype=np.int32), "ts": ts},
        key_vocab=np.array(["0"]),
    )
    assert _batch_event_lag_s(batch, now) is None
    # Without the NaT the same batch samples a real lag.
    ok = ArrayBatch(
        {"key_id": np.zeros(2, dtype=np.int32), "ts": ts[:1].repeat(2)},
        key_vocab=np.array(["0"]),
    )
    lag = _batch_event_lag_s(ok, now)
    assert lag is not None and lag == lag and lag > 0


# -- fraction buckets and the attribution-backed rescale hint ----------


def test_ledger_fractions_buckets():
    fr = flight.ledger_fractions(
        {"host": 1.0, "ingest": 1.0, "device": 1.0, "barrier": 1.0}
    )
    assert fr["host"] == 0.5  # host + ingest fold into one bucket
    assert fr["device"] == 0.25 and fr["barrier"] == 0.25
    assert abs(sum(fr.values()) - 1.0) < 0.01
    # No attributed time yet -> no fractions (not a zero division).
    assert flight.ledger_fractions({}) is None


def test_rescale_hint_ledger_device_dominated_grows():
    advice, reasons = derive_rescale_hint(
        worker_count=1,
        epoch_interval_s=10.0,
        close_p99_s=0.1,
        stall_s_per_close=0.0,
        restores_per_close=0.0,
        phase_fractions={"device": 0.4, "flush": 0.2, "host": 0.4},
    )
    assert advice == "grow"
    assert any("ledger" in r and "device" in r for r in reasons)


def test_rescale_hint_barrier_dominated_vetoes_grow():
    # Loud close latency but barrier-dominated attribution: this
    # process is waiting for peers — growing adds waiters.
    advice, reasons = derive_rescale_hint(
        worker_count=2,
        epoch_interval_s=10.0,
        close_p99_s=6.0,
        stall_s_per_close=0.0,
        restores_per_close=0.0,
        phase_fractions={"barrier": 0.7, "host": 0.3},
    )
    assert advice == "hold"
    assert any("barrier" in r for r in reasons)


def test_rescale_hint_barrier_dominated_shrinks_when_not_loud():
    advice, reasons = derive_rescale_hint(
        worker_count=2,
        epoch_interval_s=10.0,
        close_p99_s=None,
        stall_s_per_close=0.0,
        restores_per_close=0.0,
        phase_fractions={"barrier": 0.8, "host": 0.2},
    )
    assert advice == "shrink"
    assert any("barrier" in r for r in reasons)


# -- Perfetto trace_event export ---------------------------------------


def test_perfetto_trace_dump(monkeypatch, tmp_path):
    trace_dir = tmp_path / "traces"
    monkeypatch.setenv("BYTEWAX_TPU_TRACE_DIR", str(trace_dir))
    _reset_ledger()
    out = []
    flow = Dataflow("trace_df")
    s = op.input("inp", flow, TestingSource(list(range(20)), batch_size=5))
    s = op.map("double", s, lambda x: x * 2)
    op.output("out", s, TestingSink(out))
    run_main(flow, epoch_interval=ZERO_TD)
    assert out

    files = sorted(trace_dir.glob("epoch-p00-*.json"))
    assert files, list(trace_dir.iterdir())
    saw_phase_slice = False
    saw_counter = False
    counter_ts = {}  # (path, track) -> [ts, ...]
    for path in files:
        doc = json.loads(path.read_text())
        events = doc["traceEvents"]
        assert isinstance(events, list) and events
        for ev in events:
            # Chrome trace_event required fields per phase type.
            assert isinstance(ev["name"], str)
            assert ev["ph"] in ("M", "X", "C")
            assert isinstance(ev["pid"], int)
            if ev["ph"] == "X":
                assert isinstance(ev["ts"], (int, float))
                assert isinstance(ev["dur"], (int, float))
                assert ev["dur"] >= 0
                if ev.get("args", {}).get("step_id"):
                    saw_phase_slice = True
            elif ev["ph"] == "C":
                # Flow-map counter tracks: numeric args only (Chrome
                # renders each args key as a series on the track).
                saw_counter = True
                assert isinstance(ev["ts"], (int, float))
                assert ev["args"], ev
                for v in ev["args"].values():
                    assert isinstance(v, (int, float)), ev
                counter_ts.setdefault(
                    (str(path), ev["name"]), []
                ).append(ev["ts"])
    assert saw_phase_slice, "no per-step phase slices in any dump"
    # Counter tracks ride the flow-map seal: every dump after the
    # first sealed epoch carries rows/s samples...
    assert saw_counter, "no flow-map counter tracks in any dump"
    assert any(
        name.startswith("rows/s ") for (_p, name) in counter_ts
    ), sorted(counter_ts)
    # ...and each track's samples are monotone-timestamped (Perfetto
    # silently drops out-of-order counter samples).
    for (path, name), stamps in counter_ts.items():
        assert len(stamps) >= 2, (path, name, stamps)
        assert stamps == sorted(stamps), (path, name, stamps)


_OVERLAP_TRACE_FLOW = '''
import os

import bytewax_tpu.operators as op
from bytewax_tpu import xla
from bytewax_tpu.dataflow import Dataflow
from bytewax_tpu.connectors.files import FileSink
from bytewax_tpu.inputs import DynamicSource, StatelessSourcePartition


class _Part(StatelessSourcePartition):
    """Paced batches so the run spans several epochs (several
    overlapped collective flush rounds), not one EOF burst."""

    def __init__(self, worker_index):
        import time

        self._time = time
        base = worker_index * 1000
        self._batches = [
            [(f"k{{i % 5}}", float(base + b * 100 + i)) for i in range(80)]
            for b in range(4)
        ]

    def next_batch(self):
        if not self._batches:
            raise StopIteration()
        self._time.sleep(0.12)
        return self._batches.pop(0)


class Src(DynamicSource):
    def build(self, step_id, worker_index, worker_count):
        return _Part(worker_index)


flow = Dataflow("trace_ovl_df")
s = op.input("inp", flow, Src())
st = xla.stats_final("stats", s)
fmt = op.map_value("fmt", st, str)
op.output("out", fmt, FileSink({out_path!r}))
'''


def test_perfetto_overlap_collective_lane_own_tid(tmp_path):
    # Under BYTEWAX_TPU_GSYNC_OVERLAP=1 the sealed device exchange
    # runs on the collective lane while the next epoch computes: its
    # spans must land on their OWN Perfetto tid (3; named by a
    # thread_name meta), distinct from the driver (1) and device
    # pipeline (2) tracks — sharing the device tid would render as
    # nonsense nesting — and the flow-map counter tracks must emit
    # monotone-timestamped samples in the same dumps.
    trace_dir = tmp_path / "traces"
    flow_py = tmp_path / "trace_ovl_flow.py"
    out_path = str(tmp_path / "trace_ovl_out.txt")
    flow_py.write_text(_OVERLAP_TRACE_FLOW.format(out_path=out_path))

    env = dict(os.environ)
    env["PYTHONPATH"] = "/root/repo" + os.pathsep + env.get(
        "PYTHONPATH", ""
    )
    env["BYTEWAX_TPU_PLATFORM"] = "cpu"
    env["BYTEWAX_TPU_ACCEL"] = "1"
    env["BYTEWAX_TPU_DISTRIBUTED"] = "1"
    env["BYTEWAX_TPU_GLOBAL_EXCHANGE"] = "1"
    env["BYTEWAX_TPU_GSYNC_OVERLAP"] = "1"
    env["BYTEWAX_TPU_TRACE_DIR"] = str(trace_dir)
    # Batch-granular ingest: the coalescer would collapse the paced
    # source into one EOF flush and leave nothing to overlap.
    env["BYTEWAX_TPU_INGEST_TARGET_ROWS"] = "0"
    res = subprocess.run(
        [
            sys.executable,
            "-m",
            "bytewax_tpu.testing",
            f"{flow_py}:flow",
            "-p",
            "2",
            "-s",
            "0.2",
        ],
        env=env,
        cwd=tmp_path,
        capture_output=True,
        text=True,
        timeout=240,
    )
    assert res.returncode == 0, res.stderr[-3000:]

    lane_spans = []
    other_tids = set()
    counter_ts = {}
    for proc in (0, 1):
        files = sorted(trace_dir.glob(f"epoch-p{proc:02d}-*.json"))
        assert files, list(
            trace_dir.iterdir() if trace_dir.exists() else []
        )
        for path in files:
            doc = json.loads(path.read_text())
            lane_named = [
                ev
                for ev in doc["traceEvents"]
                if ev["ph"] == "M"
                and ev["name"] == "thread_name"
                and ev["args"]["name"] == "collective lane"
            ]
            assert lane_named and all(
                ev["tid"] == 3 for ev in lane_named
            ), path
            for ev in doc["traceEvents"]:
                if ev["ph"] == "X":
                    if ev["name"] == "collective_lane":
                        lane_spans.append(ev)
                    else:
                        other_tids.add(ev["tid"])
                elif ev["ph"] == "C":
                    counter_ts.setdefault(
                        (str(path), ev["name"]), []
                    ).append(ev["ts"])
    # The sealed exchange ran (both procs flush, but dumps are
    # per-process; one proc's lane spans suffice for the rendering
    # contract) and every lane span sits on tid 3.
    assert lane_spans, "no collective_lane spans in any dump"
    assert {ev["tid"] for ev in lane_spans} == {3}
    # No other span ever shares the lane's track (the collective
    # tier bypasses the per-delivery device pipeline, so this flow
    # has no device-lane spans — only the driver track plus the
    # lane's own).
    assert 3 not in other_tids, other_tids
    assert 1 in other_tids, other_tids
    # Counter tracks emit monotone-timestamped samples under overlap.
    assert counter_ts, "no flow-map counter tracks in any dump"
    for (path, name), stamps in counter_ts.items():
        assert len(stamps) >= 2, (path, name, stamps)
        assert stamps == sorted(stamps), (path, name, stamps)


# -- /healthz and /stacks ----------------------------------------------


def test_healthz_and_stacks_during_run(monkeypatch, tmp_path):
    monkeypatch.setenv("BYTEWAX_DATAFLOW_API_ENABLED", "1")
    monkeypatch.setenv("BYTEWAX_DATAFLOW_API_PORT", "13049")
    monkeypatch.chdir(tmp_path)

    captured = {}

    class _ProbePartition:
        def write_batch(self, items):
            if "health" not in captured:
                with urllib.request.urlopen(
                    "http://127.0.0.1:13049/healthz", timeout=5
                ) as resp:
                    captured["health_code"] = resp.status
                    captured["health"] = json.loads(resp.read())
                with urllib.request.urlopen(
                    "http://127.0.0.1:13049/stacks", timeout=5
                ) as resp:
                    captured["stacks"] = resp.read().decode()

        def close(self):
            pass

    from bytewax_tpu.outputs import DynamicSink

    class _ProbeSink(DynamicSink):
        def build(self, step_id, worker_index, worker_count):
            return _ProbePartition()

    flow = Dataflow("health_df")
    s = op.input("inp", flow, TestingSource([1, 2, 3]))
    op.output("out", s, _ProbeSink())
    run_main(flow)

    # Readiness: startup (handshake, agreement round, runtime builds)
    # finished before the run loop -> 200 ready from inside the run.
    assert captured["health_code"] == 200
    health = captured["health"]
    assert health["live"] is True and health["ready"] is True
    assert health["proc_id"] == 0
    assert isinstance(health["epoch"], int)
    # /stacks names every thread with a Python stack; the probe runs
    # on the main run loop.
    assert "MainThread" in captured["stacks"]
    assert "Thread " in captured["stacks"]


def test_healthz_not_ready_is_503(monkeypatch, tmp_path):
    # k8s readiness reads the status code: an un-ready process must
    # answer 503 (liveness still true in the body).
    monkeypatch.setenv("BYTEWAX_DATAFLOW_API_ENABLED", "1")
    monkeypatch.setenv("BYTEWAX_DATAFLOW_API_PORT", "13050")
    monkeypatch.chdir(tmp_path)
    from bytewax_tpu.engine.webserver import maybe_start_server

    flow = Dataflow("unready_df")
    s = op.input("inp", flow, TestingSource([1]))
    op.output("out", s, TestingSink([]))
    srv = maybe_start_server(
        flow, health_fn=lambda: {"ready": False, "phase": "startup"}
    )
    assert srv is not None
    try:
        with pytest.raises(urllib.error.HTTPError) as exc_info:
            urllib.request.urlopen(
                "http://127.0.0.1:13050/healthz", timeout=5
            )
        err = exc_info.value
        assert err.code == 503
        body = json.loads(err.read())
        assert body["live"] is True and body["ready"] is False
    finally:
        srv.shutdown()


# -- crash post-mortems ------------------------------------------------


def test_postmortem_write_unit(monkeypatch, tmp_path):
    monkeypatch.delenv("BYTEWAX_TPU_POSTMORTEM_DIR", raising=False)
    assert flight.write_postmortem(0, 0, "DeviceFault") is None

    monkeypatch.setenv("BYTEWAX_TPU_POSTMORTEM_DIR", str(tmp_path))
    flight.note_phase("host", "pm_df.step", 0.01)
    path = flight.write_postmortem(3, 2, "DeviceFault", "boom")
    assert path is not None and os.path.exists(path)
    assert os.path.basename(path) == "postmortem-3-2.json"
    doc = json.loads(open(path).read())
    assert doc["proc_id"] == 3 and doc["generation"] == 2
    assert doc["cause"] == "DeviceFault" and doc["detail"] == "boom"
    assert "counters" in doc and "tail" in doc
    # The in-flight (unsealed) epoch's attribution is the evidence a
    # sealed record can't carry.
    assert doc["ledger"]["in_flight"]["host"]["pm_df.step"] > 0


def test_postmortem_on_supervised_restart(monkeypatch, tmp_path):
    # A restartable injected fault under the supervisor dumps the
    # flight state before the backoff sleep, named by the failed
    # generation.
    faults.reset()
    pm_dir = tmp_path / "pm"
    db = tmp_path / "db"
    db.mkdir()
    init_db_dir(db, 1)
    monkeypatch.setenv(
        "BYTEWAX_TPU_FAULTS", "snapshot.commit:crash:3:x1"
    )
    monkeypatch.setenv("BYTEWAX_TPU_MAX_RESTARTS", "2")
    monkeypatch.setenv("BYTEWAX_TPU_RESTART_BACKOFF_S", "0.05")
    monkeypatch.setenv("BYTEWAX_TPU_POSTMORTEM_DIR", str(pm_dir))
    try:
        out = []
        flow = Dataflow("pm_df")
        s = op.input(
            "inp", flow, TestingSource(list(range(12)), batch_size=2)
        )
        s = op.map("id", s, lambda x: x)
        op.output("out", s, TestingSink(out))
        run_main(
            flow,
            epoch_interval=ZERO_TD,
            recovery_config=RecoveryConfig(str(db)),
        )
    finally:
        faults.reset()

    path = pm_dir / "postmortem-0-0.json"
    assert path.exists(), list(pm_dir.iterdir() if pm_dir.exists() else [])
    doc = json.loads(path.read_text())
    assert doc["cause"] == "InjectedCrash"
    assert doc["generation"] == 0
    assert "ledger" in doc and "counters" in doc and "tail" in doc


# -- comm contract: the piggyback grew, the frame inventory did not ----


def test_ledger_rides_existing_telemetry_no_new_frames():
    # The cluster ledger exchange rides the existing epoch-close
    # summary (one gsync round) — the sealed record is IN the
    # summary, and the analyzer's frame/send inventories still hold
    # with zero new control-frame kinds.
    rec = flight.FlightRecorder()
    rec.ledger_add("host", "s1", 0.01)
    rec.note_epoch_close(1, 0.002)
    summary = rec.summary(1)
    assert summary["ledger"]["epoch"] == 1
    assert summary["ledger"]["phases"]["host"]["s1"] > 0

    from bytewax_tpu.analysis import analyze_tree
    from bytewax_tpu.analysis.contracts import CONTROL_FRAMES

    assert not any("ledger" in kind for kind in CONTROL_FRAMES)
    diags, _suppressed, _project = analyze_tree()
    assert not diags, [str(d) for d in diags]


# -- the acceptance check: 2-process cluster /status ledger ------------


def test_ledger_cluster_status_piggyback_2proc(tmp_path):
    # In a real 2-process cluster, any process's /status shows BOTH
    # processes' per-epoch phase breakdowns, and each breakdown's
    # close-window phases sum to within 10% of that epoch's measured
    # close duration (floored at scheduler granularity for sub-ms
    # closes).
    flow_py = tmp_path / "ledger_flow.py"
    flow_py.write_text(
        """
import time
import bytewax_tpu.operators as op
from bytewax_tpu.dataflow import Dataflow
from bytewax_tpu.inputs import DynamicSource, StatelessSourcePartition
from bytewax_tpu.outputs import DynamicSink, StatelessSinkPartition


class _Tick(StatelessSourcePartition):
    def __init__(self):
        self._i = 0

    def next_batch(self):
        if self._i >= 40:
            raise StopIteration()
        self._i += 1
        time.sleep(0.1)
        return [("k", 1)]


class TickSource(DynamicSource):
    def build(self, step_id, worker_index, worker_count):
        return _Tick()


class _Null(StatelessSinkPartition):
    def write_batch(self, items):
        pass


class NullSink(DynamicSink):
    def build(self, step_id, worker_index, worker_count):
        return _Null()


flow = Dataflow("ledger_cluster_df")
s = op.input("inp", flow, TickSource())
s = op.stateful_map("sum", s, lambda st, v: ((st or 0) + v, (st or 0) + v))
op.output("out", s, NullSink())
"""
    )
    import socket

    ports = []
    for _ in range(2):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        ports.append(s.getsockname()[1])
        s.close()
    db = tmp_path / "db"
    db.mkdir()
    init_db_dir(db, 1)

    env = dict(os.environ)
    env["PYTHONPATH"] = "/root/repo" + os.pathsep + env.get(
        "PYTHONPATH", ""
    )
    env["BYTEWAX_TPU_PLATFORM"] = "cpu"
    env["BYTEWAX_TPU_ACCEL"] = "0"
    env["BYTEWAX_DATAFLOW_API_ENABLED"] = "1"
    env["BYTEWAX_DATAFLOW_API_PORT"] = "13051"
    env["BYTEWAX_ADDRESSES"] = ";".join(
        f"127.0.0.1:{p}" for p in ports
    )
    env["BYTEWAX_TPU_DIAL_TIMEOUT_S"] = "120"
    procs = []
    for proc_id in range(2):
        penv = dict(env)
        penv["BYTEWAX_PROCESS_ID"] = str(proc_id)
        procs.append(
            subprocess.Popen(
                [
                    sys.executable,
                    "-m",
                    "bytewax_tpu.run",
                    f"{flow_py}:flow",
                    "-s",
                    "0.3",
                    "-b",
                    "30",
                    "-r",
                    str(db),
                ],
                env=penv,
                cwd=tmp_path,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
            )
        )
    status = None
    try:
        deadline = time.monotonic() + 150
        while time.monotonic() < deadline:
            try:
                with urllib.request.urlopen(
                    "http://127.0.0.1:13051/status", timeout=2
                ) as resp:
                    got = json.loads(resp.read())
            except OSError:
                time.sleep(0.2)
                continue
            cluster = got.get("cluster", {})
            # The summary ships the PREVIOUS epoch's sealed record,
            # so wait for a close where both processes have one.
            if len(cluster) == 2 and all(
                isinstance(s.get("ledger"), dict)
                and s["ledger"].get("close")
                for s in cluster.values()
            ):
                status = got
                break
            time.sleep(0.2)
    finally:
        errs = []
        for proc in procs:
            try:
                _out, err = proc.communicate(timeout=120)
            except subprocess.TimeoutExpired:
                proc.kill()
                _out, err = proc.communicate()
            errs.append(err)
    for proc, err in zip(procs, errs):
        assert proc.returncode == 0, err[-2000:].decode(errors="replace")
    assert status is not None, "cluster ledgers never reached proc 0"
    assert set(status["cluster"]) == {"0", "1"}
    for pid in ("0", "1"):
        record = status["cluster"][pid]["ledger"]
        assert isinstance(record["epoch"], int)
        assert record["phases"], record
        # The acceptance bound: close-window phase sum within 10% of
        # the measured close duration (absolute floor for clock
        # granularity on sub-ms closes).
        close_sum = sum(record["close"].values())
        close_s = record["close_s"]
        assert abs(close_sum - close_s) <= max(
            0.10 * close_s, 0.004
        ), record
        # Full-epoch main-thread phases stay within the epoch wall.
        main_sum = _phase_sum(_MAIN_PHASES_ONLY(record["phases"]))
        assert main_sum <= record["wall_s"] * 1.10 + 0.005, record
    # Local /status carries the same ledger section for this process.
    assert "ledger" in status
    assert "phase_totals" in status["ledger"]
