"""Direct unit tests of windower/clock logic classes (model:
``/root/reference/pytests/operators/windowing/test_session_windower.py``
etc. — the reference tests logic classes directly as well as through
dataflows)."""

from datetime import datetime, timedelta, timezone

from bytewax_tpu.operators.windowing import (
    LATE_SESSION_ID,
    SessionWindower,
    SlidingWindower,
    TumblingWindower,
)

ALIGN = datetime(2022, 1, 1, tzinfo=timezone.utc)


def _t(seconds):
    return ALIGN + timedelta(seconds=seconds)


def test_sliding_intersecting_boundaries():
    logic = SlidingWindower(
        length=timedelta(seconds=10),
        offset=timedelta(seconds=5),
        align_to=ALIGN,
    ).build(None)
    # Exactly at a window open: belongs to it and the previous one.
    assert logic.intersecting_ids(_t(10)) == [1, 2]
    # Exactly at a close boundary: excluded from the closing window.
    assert 0 not in logic.intersecting_ids(_t(10))
    # Mid-window.
    assert logic.intersecting_ids(_t(7)) == [0, 1]
    # Before align_to: negative ids.
    assert logic.intersecting_ids(_t(-3)) == [-2, -1]


def test_tumbling_open_close_metadata():
    logic = TumblingWindower(
        length=timedelta(minutes=1), align_to=ALIGN
    ).build(None)
    (wid,) = logic.open_for(_t(30))
    assert wid == 0
    closed = logic.close_for(_t(59))
    assert closed == []  # close time is exclusive
    closed = logic.close_for(_t(60))
    assert [w for w, _m in closed] == [0]
    meta = closed[0][1]
    assert meta.open_time == ALIGN
    assert meta.close_time == _t(60)
    assert logic.is_empty()


def test_session_grows_and_merges():
    logic = SessionWindower(gap=timedelta(seconds=5)).build(None)
    (a,) = logic.open_for(_t(0))
    (b,) = logic.open_for(_t(20))
    assert a != b
    # Within the gap after session a: extends it, then merges with b
    # if boundaries now touch (they don't yet).
    (a2,) = logic.open_for(_t(4))
    assert a2 == a
    assert list(logic.merged()) == []
    # Pull b's open down to 16, then extend a's close 4 → 9 → 13; at
    # that point b's open (16) is within the 5s gap and they merge.
    (pre,) = logic.open_for(_t(16))
    assert pre == b
    (bridge,) = logic.open_for(_t(9))
    assert bridge == a
    (bridge2,) = logic.open_for(_t(13))
    assert bridge2 == a
    merges = list(logic.merged())
    # Session b (later open) merged into session a.
    assert merges == [(b, a)]
    # The surviving session spans 0..20.
    closed = logic.close_for(_t(100))
    assert [w for w, _m in closed] == [a]
    meta = closed[0][1]
    assert meta.open_time == _t(0)
    assert meta.close_time == _t(20)
    assert meta.merged_ids == {b}


def test_session_never_reuses_ids():
    logic = SessionWindower(gap=timedelta(seconds=1)).build(None)
    (a,) = logic.open_for(_t(0))
    logic.close_for(_t(100))
    (b,) = logic.open_for(_t(200))
    assert b != a
    assert not logic.is_empty()  # sessions never report empty


def test_session_late_sentinel():
    logic = SessionWindower(gap=timedelta(seconds=1)).build(None)
    assert list(logic.late_for(_t(0))) == [LATE_SESSION_ID]


def test_sliding_snapshot_roundtrip():
    windower = SlidingWindower(
        length=timedelta(seconds=10),
        offset=timedelta(seconds=5),
        align_to=ALIGN,
    )
    logic = windower.build(None)
    logic.open_for(_t(7))
    snap = logic.snapshot()
    resumed = windower.build(snap)
    assert resumed.notify_at() == logic.notify_at()
    closed = resumed.close_for(_t(100))
    assert sorted(w for w, _m in closed) == [0, 1]
