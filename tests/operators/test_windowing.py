"""Windowing tests (model:
``/root/reference/pytests/operators/windowing/``)."""

from datetime import datetime, timedelta, timezone

import pytest

import bytewax_tpu.operators as op
import bytewax_tpu.operators.windowing as w
from bytewax_tpu.dataflow import Dataflow
from bytewax_tpu.operators.windowing import (
    LATE_SESSION_ID,
    EventClock,
    SessionWindower,
    SlidingWindower,
    SystemClock,
    TumblingWindower,
    WindowMetadata,
    ZERO_TD,
)
from bytewax_tpu.testing import (
    TestingSink,
    TestingSource,
    TimeTestingGetter,
    run_main,
)

ALIGN_TO = datetime(2022, 1, 1, tzinfo=timezone.utc)


def _ts_clock():
    # wait=10s gives load tolerance: EventClock watermarks advance
    # with wall-clock time, so with wait=0 any ~1s stall between
    # single-item batches (compile, CI load) flips the next on-time
    # item late.  The deliberate lateness scenarios in this file use
    # event-time gaps of 29-59s, far above the wait, and every window
    # still closes at EOF.
    return EventClock(
        ts_getter=lambda item: item[0],
        wait_for_system_duration=timedelta(seconds=10),
    )


def test_tumbling_fold_window(entry_point):
    inp = [
        (ALIGN_TO + timedelta(seconds=s), val)
        for s, val in [(1, 1), (5, 2), (61, 10), (62, 20)]
    ]
    out = []
    flow = Dataflow("test_df")
    s = op.input("inp", flow, TestingSource(inp))
    keyed = op.key_on("key", s, lambda _x: "ALL")
    wo = w.fold_window(
        "sum",
        keyed,
        _ts_clock(),
        TumblingWindower(length=timedelta(minutes=1), align_to=ALIGN_TO),
        builder=lambda: 0,
        folder=lambda acc, item: acc + item[1],
        merger=lambda a, b: a + b,
    )
    op.output("out", wo.down, TestingSink(out))
    entry_point(flow)
    assert sorted(out) == [("ALL", (0, 3)), ("ALL", (1, 30))]


def test_tumbling_window_metadata(entry_point):
    inp = [(ALIGN_TO + timedelta(seconds=1), 1)]
    metas = []
    flow = Dataflow("test_df")
    s = op.input("inp", flow, TestingSource(inp))
    keyed = op.key_on("key", s, lambda _x: "ALL")
    wo = w.fold_window(
        "sum",
        keyed,
        _ts_clock(),
        TumblingWindower(length=timedelta(minutes=1), align_to=ALIGN_TO),
        builder=lambda: 0,
        folder=lambda acc, item: acc + item[1],
        merger=lambda a, b: a + b,
    )
    op.output("meta", wo.meta, TestingSink(metas))
    op.output("down", wo.down, TestingSink([]))
    entry_point(flow)
    assert metas == [
        (
            "ALL",
            (
                0,
                WindowMetadata(ALIGN_TO, ALIGN_TO + timedelta(minutes=1)),
            ),
        )
    ]


def test_sliding_window_overlap(entry_point):
    # length 10s, offset 5s: an item at t=7 falls in windows 0 and 1.
    inp = [(ALIGN_TO + timedelta(seconds=7), 1)]
    out = []
    flow = Dataflow("test_df")
    s = op.input("inp", flow, TestingSource(inp))
    keyed = op.key_on("key", s, lambda _x: "ALL")
    wo = w.collect_window(
        "coll",
        keyed,
        _ts_clock(),
        SlidingWindower(
            length=timedelta(seconds=10),
            offset=timedelta(seconds=5),
            align_to=ALIGN_TO,
        ),
    )
    op.output("out", wo.down, TestingSink(out))
    entry_point(flow)
    vals = sorted((wid, [v for _ts, v in items]) for _k, (wid, items) in out)
    assert vals == [(0, [1]), (1, [1])]


def test_late_items_go_to_late_stream(entry_point):
    inp = [
        (ALIGN_TO + timedelta(seconds=60), "on-time"),
        (ALIGN_TO + timedelta(seconds=1), "late"),  # behind watermark
    ]
    down = []
    late = []
    flow = Dataflow("test_df")
    s = op.input("inp", flow, TestingSource(inp))
    keyed = op.key_on("key", s, lambda _x: "ALL")
    wo = w.collect_window(
        "coll",
        keyed,
        _ts_clock(),
        TumblingWindower(length=timedelta(minutes=1), align_to=ALIGN_TO),
    )
    op.output("down", wo.down, TestingSink(down))
    op.output("late", wo.late, TestingSink(late))
    entry_point(flow)
    assert late == [("ALL", (0, (ALIGN_TO + timedelta(seconds=1), "late")))]
    assert len(down) == 1


def test_session_window_merge(entry_point):
    # Two separated sessions, then a bridging item within the gap of
    # both merges them into one.  The clock waits long enough that the
    # out-of-order bridge is not late.
    ts = [0, 10, 5]
    inp = [(ALIGN_TO + timedelta(seconds=s), s) for s in ts]
    out = []
    metas = []
    flow = Dataflow("test_df")
    s = op.input("inp", flow, TestingSource(inp, batch_size=1))
    keyed = op.key_on("key", s, lambda _x: "ALL")
    clock = EventClock(
        ts_getter=lambda item: item[0],
        wait_for_system_duration=timedelta(seconds=60),
    )
    wo = w.collect_window(
        "coll",
        keyed,
        clock,
        SessionWindower(gap=timedelta(seconds=5)),
        ordered=False,
    )
    op.output("out", wo.down, TestingSink(out))
    op.output("meta", wo.meta, TestingSink(metas))
    entry_point(flow)
    assert len(out) == 1
    _k, (wid, items) = out[0]
    assert sorted(v for _ts, v in items) == [0, 5, 10]
    _k, (_wid, meta) = metas[0]
    assert meta.open_time == ALIGN_TO
    assert meta.close_time == ALIGN_TO + timedelta(seconds=10)
    assert len(meta.merged_ids) == 1


def test_session_late(entry_point):
    inp = [
        (ALIGN_TO + timedelta(seconds=30), "a"),
        (ALIGN_TO + timedelta(seconds=1), "late"),
    ]
    late = []
    flow = Dataflow("test_df")
    s = op.input("inp", flow, TestingSource(inp))
    keyed = op.key_on("key", s, lambda _x: "ALL")
    wo = w.collect_window(
        "coll",
        keyed,
        _ts_clock(),
        SessionWindower(gap=timedelta(seconds=4)),
    )
    op.output("down", wo.down, TestingSink([]))
    op.output("late", wo.late, TestingSink(late))
    entry_point(flow)
    assert late == [
        ("ALL", (LATE_SESSION_ID, (ALIGN_TO + timedelta(seconds=1), "late")))
    ]


def test_reduce_window(entry_point):
    inp = [
        (ALIGN_TO + timedelta(seconds=1), 5),
        (ALIGN_TO + timedelta(seconds=2), 3),
        (ALIGN_TO + timedelta(seconds=3), 9),
    ]
    out = []
    flow = Dataflow("test_df")
    s = op.input("inp", flow, TestingSource(inp))
    keyed = op.key_on("key", s, lambda _x: "ALL")
    wo = w.reduce_window(
        "max",
        keyed,
        _ts_clock(),
        TumblingWindower(length=timedelta(minutes=1), align_to=ALIGN_TO),
        lambda a, b: a if a[1] >= b[1] else b,
    )
    op.output("out", wo.down, TestingSink(out))
    entry_point(flow)
    assert out == [("ALL", (0, (ALIGN_TO + timedelta(seconds=3), 9)))]


def test_max_min_window(entry_point):
    inp = [
        (ALIGN_TO + timedelta(seconds=1), 5),
        (ALIGN_TO + timedelta(seconds=2), 3),
    ]
    maxes = []
    mins = []
    flow = Dataflow("test_df")
    s = op.input("inp", flow, TestingSource(inp))
    keyed = op.key_on("key", s, lambda _x: "ALL")
    wo_max = w.max_window(
        "max",
        keyed,
        _ts_clock(),
        TumblingWindower(length=timedelta(minutes=1), align_to=ALIGN_TO),
        by=lambda item: item[1],
    )
    wo_min = w.min_window(
        "min",
        keyed,
        _ts_clock(),
        TumblingWindower(length=timedelta(minutes=1), align_to=ALIGN_TO),
        by=lambda item: item[1],
    )
    op.output("max_out", wo_max.down, TestingSink(maxes))
    op.output("min_out", wo_min.down, TestingSink(mins))
    entry_point(flow)
    assert maxes == [("ALL", (0, (ALIGN_TO + timedelta(seconds=1), 5)))]
    assert mins == [("ALL", (0, (ALIGN_TO + timedelta(seconds=2), 3)))]


def test_count_window(entry_point):
    inp = [
        (ALIGN_TO + timedelta(seconds=1), "apple"),
        (ALIGN_TO + timedelta(seconds=2), "apple"),
        (ALIGN_TO + timedelta(seconds=3), "pear"),
    ]
    out = []
    flow = Dataflow("test_df")
    s = op.input("inp", flow, TestingSource(inp))
    wo = w.count_window(
        "count",
        s,
        _ts_clock(),
        TumblingWindower(length=timedelta(minutes=1), align_to=ALIGN_TO),
        key=lambda item: item[1],
    )
    op.output("out", wo.down, TestingSink(out))
    entry_point(flow)
    assert sorted(out) == [("apple", (0, 2)), ("pear", (0, 1))]


def test_collect_window_set_and_dict(entry_point):
    inp = [
        (ALIGN_TO + timedelta(seconds=1), ("x", 1)),
        (ALIGN_TO + timedelta(seconds=2), ("x", 2)),
        (ALIGN_TO + timedelta(seconds=3), ("y", 9)),
    ]
    out = []
    flow = Dataflow("test_df")
    s = op.input("inp", flow, TestingSource(inp))
    keyed = op.key_on("key", s, lambda _x: "ALL")
    unpacked = op.map_value("unpack", keyed, lambda item: item[1])
    wo = w.collect_window(
        "coll",
        unpacked,
        EventClock(
            ts_getter=lambda _kv: ALIGN_TO,
            wait_for_system_duration=timedelta(seconds=60),
        ),
        TumblingWindower(length=timedelta(minutes=1), align_to=ALIGN_TO),
        into=dict,
    )
    op.output("out", wo.down, TestingSink(out))
    entry_point(flow)
    assert out == [("ALL", (0, {"x": 2, "y": 9}))]


def test_join_window(entry_point):
    clock = EventClock(
        ts_getter=lambda _v: ALIGN_TO + timedelta(seconds=1),
        wait_for_system_duration=timedelta(seconds=60),
    )
    out = []
    flow = Dataflow("test_df")
    lefts = op.input("left", flow, TestingSource([("k", 1)]))
    rights = op.input("right", flow, TestingSource([("k", "x")]))
    wo = w.join_window(
        "join",
        clock,
        TumblingWindower(length=timedelta(minutes=1), align_to=ALIGN_TO),
        lefts,
        rights,
    )
    op.output("out", wo.down, TestingSink(out))
    entry_point(flow)
    assert out == [("k", (0, (1, "x")))]


def test_event_clock_watermark_advances_with_system_time():
    getter = TimeTestingGetter(ALIGN_TO)
    clock = EventClock(
        ts_getter=lambda item: item[0],
        wait_for_system_duration=timedelta(seconds=10),
        now_getter=getter.get,
    )
    logic = clock.build(None)
    logic.before_batch()
    ts, watermark = logic.on_item((ALIGN_TO, "x"))
    assert ts == ALIGN_TO
    assert watermark == ALIGN_TO - timedelta(seconds=10)
    # Watermark advances as system time passes without new items.
    getter.advance(timedelta(seconds=7))
    assert logic.on_notify() == ALIGN_TO - timedelta(seconds=3)


def test_event_clock_watermark_never_regresses():
    getter = TimeTestingGetter(ALIGN_TO)
    clock = EventClock(
        ts_getter=lambda item: item[0],
        wait_for_system_duration=ZERO_TD,
        now_getter=getter.get,
    )
    logic = clock.build(None)
    logic.before_batch()
    _, wm1 = logic.on_item((ALIGN_TO + timedelta(seconds=60), "x"))
    # An out-of-order item must not pull the watermark back.
    _, wm2 = logic.on_item((ALIGN_TO + timedelta(seconds=1), "y"))
    assert wm2 == wm1


def test_system_clock_runs(entry_point):
    inp = list(range(5))
    out = []
    flow = Dataflow("test_df")
    s = op.input("inp", flow, TestingSource(inp))
    keyed = op.key_on("key", s, lambda _x: "ALL")
    wo = w.collect_window(
        "coll",
        keyed,
        SystemClock(),
        TumblingWindower(
            length=timedelta(hours=1),
            align_to=ALIGN_TO,
        ),
    )
    op.output("out", wo.down, TestingSink(out))
    entry_point(flow)
    # Everything lands in one window, closed at EOF.
    assert len(out) == 1
    assert out[0][1][1] == [0, 1, 2, 3, 4]


def test_window_recovery(tmp_path):
    from bytewax_tpu.recovery import RecoveryConfig, init_db_dir

    init_db_dir(tmp_path, 1)
    rc = RecoveryConfig(str(tmp_path))
    # ABORT (not EOF): EOF closes all windows via the UTC_MAX
    # watermark, so open-window state is only carried across crashes.
    inp = [
        (ALIGN_TO + timedelta(seconds=1), 1),
        (ALIGN_TO + timedelta(seconds=2), 2),
        TestingSource.ABORT(),
        (ALIGN_TO + timedelta(seconds=3), 4),
        (ALIGN_TO + timedelta(seconds=70), 100),
    ]
    out = []
    flow = Dataflow("test_df")
    s = op.input("inp", flow, TestingSource(inp))
    keyed = op.key_on("key", s, lambda _x: "ALL")
    # wait_for_system_duration must cover the clock gap across the two
    # executions; use a large wait so nothing is late.
    clock = EventClock(
        ts_getter=lambda item: item[0],
        wait_for_system_duration=timedelta(days=365 * 100),
    )
    wo = w.fold_window(
        "sum",
        keyed,
        clock,
        TumblingWindower(length=timedelta(minutes=1), align_to=ALIGN_TO),
        builder=lambda: 0,
        folder=lambda acc, item: acc + item[1],
        merger=lambda a, b: a + b,
    )
    op.output("out", wo.down, TestingSink(out))

    run_main(flow, epoch_interval=ZERO_TD, recovery_config=rc)
    assert out == []  # crashed with windows still open

    run_main(flow, epoch_interval=ZERO_TD, recovery_config=rc)
    assert sorted(out) == [("ALL", (0, 7)), ("ALL", (1, 100))]


def test_sliding_offset_longer_than_length_raises():
    with pytest.raises(ValueError, match="offset"):
        SlidingWindower(
            length=timedelta(seconds=1),
            offset=timedelta(seconds=10),
            align_to=ALIGN_TO,
        )


def test_join_window_product_merge_keeps_all_values(entry_point):
    # Session merge in product mode must concatenate both windows'
    # values, not drop the absorbed side.
    clock = EventClock(
        ts_getter=lambda v: v[1],
        wait_for_system_duration=timedelta(seconds=60),
    )
    out = []
    flow = Dataflow("test_df")
    # Side 0 sees values in two sessions that a bridge then merges.
    left = op.input(
        "left",
        flow,
        TestingSource(
            [
                ("k", ("x", ALIGN_TO)),
                ("k", ("y", ALIGN_TO + timedelta(seconds=10))),
                ("k", ("bridge", ALIGN_TO + timedelta(seconds=5))),
            ],
            batch_size=1,
        ),
    )
    right = op.input("right", flow, TestingSource([("k", ("r", ALIGN_TO))]))
    wo = w.join_window(
        "join",
        clock,
        w.SessionWindower(gap=timedelta(seconds=5)),
        left,
        right,
        insert_mode="product",
        emit_mode="final",
    )
    op.output("out", wo.down, TestingSink(out))
    entry_point(flow)
    rows = [row for _k, (_wid, row) in out]
    left_vals = sorted(v[0] for v, _r in rows)
    assert left_vals == ["bridge", "x", "y"]
