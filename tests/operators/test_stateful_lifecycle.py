"""Stateful logic lifecycle state machine (model:
``/root/reference/pytests/operators/test_stateful.py``): every hook
emits its state transition; class flags control retention."""

from datetime import datetime, timedelta, timezone
from typing import Any, List, Optional, Tuple

import bytewax_tpu.operators as op
from bytewax_tpu.dataflow import Dataflow
from bytewax_tpu.operators import StatefulLogic
from bytewax_tpu.testing import TestingSink, TestingSource, run_main

ZERO_TD = timedelta(seconds=0)


class BaseTestLogic(StatefulLogic):
    item_triggers_notify = False
    after_item = StatefulLogic.RETAIN
    after_notify = StatefulLogic.RETAIN
    after_eof = StatefulLogic.RETAIN

    def __init__(self, state: Any):
        self._notify_at: Optional[datetime] = None
        self._state = state if state is not None else "NEW"

    def on_item(self, value: Any) -> Tuple[List[Any], bool]:
        if self.item_triggers_notify:
            self._notify_at = datetime.now(timezone.utc)
        old_state = self._state
        self._state = "ITEM"
        return ([(old_state, self._state)], self.after_item)

    def on_notify(self) -> Tuple[List[Any], bool]:
        self._notify_at = None
        old_state = self._state
        self._state = "NOTIFY"
        return ([(old_state, self._state)], self.after_notify)

    def on_eof(self) -> Tuple[List[Any], bool]:
        old_state = self._state
        self._state = "EOF"
        return ([(old_state, self._state)], self.after_eof)

    def notify_at(self) -> Optional[datetime]:
        return self._notify_at

    def snapshot(self) -> Any:
        return self._state


def _run(logic_cls, inp):
    out = []
    flow = Dataflow("test_df")
    s = op.input("inp", flow, TestingSource(inp))
    s = op.key_on("key", s, lambda _x: "ALL")
    s = op.stateful("stateful", s, logic_cls)
    op.output("out", s, TestingSink(out))
    run_main(flow, epoch_interval=ZERO_TD)
    return out


def test_stateful_on_item_discard():
    class TestLogic(BaseTestLogic):
        after_item = StatefulLogic.DISCARD

    out = _run(TestLogic, [1, 2, TestingSource.ABORT()])
    # Discard after each item: the logic is rebuilt fresh every time.
    assert out == [
        ("ALL", ("NEW", "ITEM")),
        ("ALL", ("NEW", "ITEM")),
    ]


def test_stateful_on_item_retain():
    class TestLogic(BaseTestLogic):
        after_item = StatefulLogic.RETAIN

    out = _run(TestLogic, [1, 2, TestingSource.ABORT()])
    assert out == [
        ("ALL", ("NEW", "ITEM")),
        ("ALL", ("ITEM", "ITEM")),
    ]


def test_stateful_on_notify_discard():
    class TestLogic(BaseTestLogic):
        item_triggers_notify = True
        after_notify = StatefulLogic.DISCARD

    out = _run(TestLogic, [1, 2, TestingSource.ABORT()])
    assert out == [
        ("ALL", ("NEW", "ITEM")),
        ("ALL", ("ITEM", "NOTIFY")),
        ("ALL", ("NEW", "ITEM")),
        ("ALL", ("ITEM", "NOTIFY")),
    ]


def test_stateful_on_notify_retain():
    class TestLogic(BaseTestLogic):
        item_triggers_notify = True
        after_notify = StatefulLogic.RETAIN

    out = _run(TestLogic, [1, 2, TestingSource.ABORT()])
    assert out == [
        ("ALL", ("NEW", "ITEM")),
        ("ALL", ("ITEM", "NOTIFY")),
        ("ALL", ("NOTIFY", "ITEM")),
        ("ALL", ("ITEM", "NOTIFY")),
    ]


def _run_with_recovery(logic_cls, inp, recovery_config):
    out = []
    flow = Dataflow("test_df")
    s = op.input("inp", flow, TestingSource(inp))
    s = op.key_on("key", s, lambda _x: "ALL")
    s = op.stateful("stateful", s, logic_cls)
    op.output("out", s, TestingSink(out))
    run_main(flow, epoch_interval=ZERO_TD, recovery_config=recovery_config)
    return out


def test_stateful_on_eof_discard(recovery_config):
    # Reference pattern (test_stateful.py:151-170): a recovery
    # continuation past EOF() proves the discard was durable — the
    # resumed item sees a fresh logic.
    class TestLogic(BaseTestLogic):
        after_eof = StatefulLogic.DISCARD

    inp = [1, TestingSource.EOF(), 2, TestingSource.ABORT()]
    out = _run_with_recovery(TestLogic, inp, recovery_config)
    assert out == [
        ("ALL", ("NEW", "ITEM")),
        ("ALL", ("ITEM", "EOF")),
    ]
    out2 = _run_with_recovery(TestLogic, inp, recovery_config)
    assert out2 == [("ALL", ("NEW", "ITEM"))]


def test_stateful_on_eof_retain(recovery_config):
    # The continuation's item must see the state retained across EOF.
    class TestLogic(BaseTestLogic):
        after_eof = StatefulLogic.RETAIN

    inp = [1, TestingSource.EOF(), 2, TestingSource.ABORT()]
    out = _run_with_recovery(TestLogic, inp, recovery_config)
    assert out == [
        ("ALL", ("NEW", "ITEM")),
        ("ALL", ("ITEM", "EOF")),
    ]
    out2 = _run_with_recovery(TestLogic, inp, recovery_config)
    assert out2 == [("ALL", ("EOF", "ITEM"))]


def test_stateful_resume_state_passed_to_builder(recovery_config):
    class TestLogic(BaseTestLogic):
        after_item = StatefulLogic.RETAIN

    inp = [1, TestingSource.ABORT(), 2]
    out = []
    flow = Dataflow("test_df")
    s = op.input("inp", flow, TestingSource(inp))
    s = op.key_on("key", s, lambda _x: "ALL")
    s = op.stateful("stateful", s, TestLogic)
    op.output("out", s, TestingSink(out))
    run_main(flow, epoch_interval=ZERO_TD, recovery_config=recovery_config)
    assert out == [("ALL", ("NEW", "ITEM"))]

    out.clear()
    run_main(flow, epoch_interval=ZERO_TD, recovery_config=recovery_config)
    # The snapshotted state "ITEM" is passed to the rebuilt logic;
    # this run exhausts the input so EOF also fires.
    assert out == [
        ("ALL", ("ITEM", "ITEM")),
        ("ALL", ("ITEM", "EOF")),
    ]
