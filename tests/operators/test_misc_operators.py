"""Remaining operator semantics (models:
``/root/reference/pytests/operators/test_collect.py``,
``test_enrich_cached.py``; inputs helper tests)."""

import time
from datetime import datetime, timedelta, timezone

import bytewax_tpu.operators as op
from bytewax_tpu.dataflow import Dataflow
from bytewax_tpu.operators import TTLCache
from bytewax_tpu.testing import (
    TestingSink,
    TestingSource,
    TimeTestingGetter,
    run_main,
)


def test_collect_max_size(entry_point):
    inp = [("k", i) for i in range(7)]
    out = []
    flow = Dataflow("test_df")
    s = op.input("inp", flow, TestingSource(inp))
    c = op.collect("collect", s, timeout=timedelta(seconds=10), max_size=3)
    op.output("out", c, TestingSink(out))
    entry_point(flow)
    # Size-triggered flushes of 3, then the remainder at EOF.
    assert out == [
        ("k", [0, 1, 2]),
        ("k", [3, 4, 5]),
        ("k", [6]),
    ]


def test_collect_timeout():
    # A mid-stream pause longer than the timeout flushes the batch.
    inp = [
        ("k", 0),
        ("k", 1),
        TestingSource.PAUSE(for_duration=timedelta(seconds=1.2)),
        ("k", 2),
    ]
    out = []
    flow = Dataflow("test_df")
    s = op.input("inp", flow, TestingSource(inp))
    c = op.collect("collect", s, timeout=timedelta(seconds=0.5), max_size=10)
    op.output("out", c, TestingSink(out))
    run_main(flow)
    assert out == [("k", [0, 1]), ("k", [2])]


def test_enrich_cached_caches_within_ttl():
    calls = []

    def getter(k):
        calls.append(k)
        return k.upper()

    fake = TimeTestingGetter(datetime(2022, 1, 1, tzinfo=timezone.utc))
    out = []
    flow = Dataflow("test_df")
    s = op.input("inp", flow, TestingSource(["a", "a", "b"]))
    e = op.enrich_cached(
        "enrich",
        s,
        getter,
        lambda cache, x: (x, cache.get(x)),
        ttl=timedelta(minutes=1),
        _now_getter=fake.get,
    )
    op.output("out", e, TestingSink(out))
    run_main(flow)
    assert out == [("a", "A"), ("a", "A"), ("b", "B")]
    assert calls == ["a", "b"]  # second "a" served from cache


def test_ttl_cache_expiry():
    calls = []
    fake = TimeTestingGetter(datetime(2022, 1, 1, tzinfo=timezone.utc))
    cache = TTLCache(lambda k: calls.append(k) or len(calls), fake.get, timedelta(seconds=30))
    assert cache.get("x") == 1
    assert cache.get("x") == 1
    fake.advance(timedelta(seconds=31))
    assert cache.get("x") == 2  # expired, re-fetched
    cache.remove("x")
    assert cache.get("x") == 3


def test_pause_sentinel_delays_items():
    inp = [1, TestingSource.PAUSE(for_duration=timedelta(seconds=0.5)), 2]
    stamps = []
    out = []
    flow = Dataflow("test_df")
    s = op.input("inp", flow, TestingSource(inp))
    s = op.map("stamp", s, lambda x: (stamps.append(time.monotonic()), x)[1])
    op.output("out", s, TestingSink(out))
    run_main(flow)
    assert out == [1, 2]
    assert stamps[1] - stamps[0] >= 0.45


def test_batch_helpers():
    from bytewax_tpu.inputs import batch, batch_getter, batch_getter_ex

    assert list(batch(iter(range(5)), 2)) == [[0, 1], [2, 3], [4]]

    items = iter([1, 2, None, 3])
    g = batch_getter(lambda: next(items), 10)
    assert next(g) == [1, 2]

    items2 = iter([1, 2])

    def getter_ex():
        try:
            return next(items2)
        except StopIteration:
            raise IndexError() from None

    g2 = batch_getter_ex(getter_ex, 10)
    assert next(g2) == [1, 2]


def test_batch_async():
    import asyncio

    from bytewax_tpu.inputs import batch_async

    async def agen():
        for i in range(5):
            yield i

    batches = list(batch_async(agen(), timeout=timedelta(seconds=1), batch_size=2))
    assert [b for b in batches if b] == [[0, 1], [2, 3], [4]]


def test_then_returns_chainable_windowout():
    # `.then` through an operator returning a dataclass bundle.
    from datetime import datetime, timezone

    import bytewax_tpu.operators.windowing as w
    from bytewax_tpu.operators.windowing import EventClock, TumblingWindower

    align = datetime(2022, 1, 1, tzinfo=timezone.utc)
    out = []
    flow = Dataflow("test_df")
    wo = (
        op.input("inp", flow, TestingSource([align]))
        .then(op.key_on, "key", lambda _x: "ALL")
        .then(
            w.collect_window,
            "cw",
            EventClock(ts_getter=lambda x: x, wait_for_system_duration=timedelta(0)),
            TumblingWindower(length=timedelta(minutes=1), align_to=align),
        )
    )
    op.output("out", wo.down, TestingSink(out))
    run_main(flow)
    assert len(out) == 1


def test_batch_async_slow_producer_preserves_inflight():
    # A producer slower than the gather timeout yields partial/empty
    # batches, and the in-flight anext survives across timeouts so no
    # item is ever lost or duplicated.
    import asyncio
    from datetime import timedelta

    from bytewax_tpu.inputs import batch_async

    async def agen():
        for i in range(6):
            await asyncio.sleep(0.03)  # slower than the 20ms timeout
            yield i

    batcher = batch_async(
        agen(), timeout=timedelta(seconds=0.02), batch_size=3
    )
    got = []
    rounds = 0
    for batch in batcher:
        got.extend(batch)
        rounds += 1
        assert rounds < 100, "batcher never finished"
    assert got == [0, 1, 2, 3, 4, 5]
    assert rounds > 3  # timeouts produced partial/empty rounds


def test_simple_polling_source_snapshot_resume():
    """SimplePollingSource.snapshot/resume hooks round-trip through
    the partition (reference parity: ``inputs.py:395-452``)."""
    from datetime import timedelta

    from bytewax_tpu.inputs import SimplePollingSource
    from bytewax_tpu.testing import poll_next_batch

    class Cursor(SimplePollingSource):
        def __init__(self):
            super().__init__(interval=timedelta(0))
            self.at = 0
            self.resumed_with = None

        def next_item(self):
            self.at += 1
            return self.at

        def snapshot(self):
            return self.at

        def resume(self, resume_state):
            self.resumed_with = resume_state
            self.at = resume_state

    src = Cursor()
    part = src.build_part("poll", "singleton", None)
    assert poll_next_batch(part) == [1]
    assert poll_next_batch(part) == [2]
    state = part.snapshot()
    assert state == 2

    src2 = Cursor()
    part2 = src2.build_part("poll", "singleton", state)
    assert src2.resumed_with == 2
    assert poll_next_batch(part2) == [3]
