"""Connector tests: file/CSV/dir sources and sinks, resume semantics."""

from datetime import timedelta

import bytewax_tpu.operators as op
from bytewax_tpu.connectors.files import (
    CSVSource,
    DirSink,
    DirSource,
    FileSink,
    FileSource,
)
from bytewax_tpu.dataflow import Dataflow
from bytewax_tpu.recovery import RecoveryConfig, init_db_dir
from bytewax_tpu.testing import TestingSink, TestingSource, run_main

ZERO_TD = timedelta(seconds=0)


def test_file_source(tmp_path):
    path = tmp_path / "in.txt"
    path.write_text("a\nb\nc\n")
    out = []
    flow = Dataflow("test_df")
    s = op.input("inp", flow, FileSource(path))
    op.output("out", s, TestingSink(out))
    run_main(flow)
    assert out == ["a", "b", "c"]


def test_csv_source_snapshot_mid_file(tmp_path):
    # batch_size=1 forces snapshots mid-file; tell() must stay usable.
    path = tmp_path / "in.csv"
    rows = "".join(f"r{i},v{i}\n" for i in range(10))
    path.write_text("name,val\n" + rows)
    db = tmp_path / "db"
    db.mkdir()
    init_db_dir(db, 1)
    out = []
    flow = Dataflow("test_df")
    s = op.input("inp", flow, CSVSource(path, batch_size=1))
    op.output("out", s, TestingSink(out))
    run_main(flow, epoch_interval=ZERO_TD, recovery_config=RecoveryConfig(db))
    assert len(out) == 10
    assert out[0] == {"name": "r0", "val": "v0"}


def test_dir_source(tmp_path):
    d = tmp_path / "data"
    d.mkdir()
    (d / "one.txt").write_text("1\n2\n")
    (d / "two.txt").write_text("3\n")
    out = []
    flow = Dataflow("test_df")
    s = op.input("inp", flow, DirSource(d, glob_pat="*.txt"))
    op.output("out", s, TestingSink(out))
    run_main(flow)
    assert sorted(out) == ["1", "2", "3"]


def test_file_sink_truncate_on_resume(tmp_path):
    inp = ["a", "b", TestingSource.EOF(), "c"]
    out_path = tmp_path / "out.txt"
    db = tmp_path / "db"
    db.mkdir()
    init_db_dir(db, 1)
    rc = RecoveryConfig(db)

    flow = Dataflow("test_df")
    s = op.input("inp", flow, TestingSource(inp))
    s = op.key_on("key", s, lambda _x: "k")
    op.output("out", s, FileSink(out_path))

    run_main(flow, epoch_interval=ZERO_TD, recovery_config=rc)
    assert out_path.read_text() == "a\nb\n"

    run_main(flow, epoch_interval=ZERO_TD, recovery_config=rc)
    assert out_path.read_text() == "a\nb\nc\n"


def test_dir_sink_routes_by_key(tmp_path):
    d = tmp_path / "outdir"
    d.mkdir()
    inp = [("a", "1"), ("b", "2")]
    flow = Dataflow("test_df")
    s = op.input("inp", flow, TestingSource(inp))
    op.output(
        "out",
        s,
        DirSink(d, file_count=2, assign_file=lambda k: 0 if k == "a" else 1),
    )
    run_main(flow)
    assert (d / "part_0").read_text() == "1\n"
    assert (d / "part_1").read_text() == "2\n"


def test_demo_source_resume_continues_rng():
    # Snapshot mid-stream and rebuild: the resumed partition must
    # continue the RNG sequence, matching an uninterrupted run.
    from bytewax_tpu.connectors.demo import RandomMetricSource

    src = RandomMetricSource("m", interval=ZERO_TD, count=6, seed=123)

    full_part = src.build_part("s", "m", None)
    full = [full_part.next_batch()[0][1] for _ in range(6)]

    part = src.build_part("s", "m", None)
    first_half = [part.next_batch()[0][1] for _ in range(3)]
    snap = part.snapshot()

    resumed = src.build_part("s", "m", snap)
    second_half = [resumed.next_batch()[0][1] for _ in range(3)]

    assert first_half + second_half == full


def test_dir_sink_ten_plus_files(tmp_path):
    # >=10 files: assign_file index must map to the matching
    # file_namer index despite lexicographic name ordering.
    d = tmp_path / "outdir"
    d.mkdir()
    inp = [(str(i), f"v{i}") for i in range(12)]
    flow = Dataflow("test_df")
    s = op.input("inp", flow, TestingSource(inp))
    op.output(
        "out",
        s,
        DirSink(d, file_count=12, assign_file=lambda k: int(k)),
    )
    run_main(flow)
    for i in range(12):
        assert (d / f"part_{i}").read_text() == f"v{i}\n", i


def test_csv_source_dictreader_kwargs(tmp_path):
    path = tmp_path / "in.csv"
    path.write_text("a,b\n1,2,3\n")
    out = []
    flow = Dataflow("test_df")
    s = op.input("inp", flow, CSVSource(path, restkey="extra", restval=""))
    op.output("out", s, TestingSink(out))
    run_main(flow)
    assert out == [{"a": "1", "b": "2", "extra": ["3"]}]
