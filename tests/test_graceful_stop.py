"""Graceful drain-to-stop (tentpole of the autoscaling-loop PR;
docs/recovery.md "Graceful drain-to-stop").

A cooperative stop request (``request_stop()`` / SIGTERM / ``POST
/stop``) drains the execution at the next epoch close — pipelines
flushed, DLQ flushed, snapshots committed — and the entry point
returns a typed ``GracefulStop`` instead of unwinding through the
supervisor; resuming the store replays ZERO epochs.  Everything here
is fast and single-process/in-process (tier-1, so the drain path is
exercised on every run); the clustered stop vote riding the
epoch-close gsync round is exercised end-to-end by the slow
supervisor integration tests in ``test_supervise.py``.
"""

import json
import urllib.error
import urllib.request
from datetime import timedelta

import pytest

import bytewax_tpu.operators as op
from bytewax_tpu.dataflow import Dataflow
from bytewax_tpu.engine import driver, flight
from bytewax_tpu.engine.driver import request_stop, run_main
from bytewax_tpu.engine.recovery_store import RecoveryStore
from bytewax_tpu.errors import GracefulStop
from bytewax_tpu.recovery import RecoveryConfig, init_db_dir
from bytewax_tpu.testing import TestingSink, TestingSource

ZERO_TD = timedelta(seconds=0)


@pytest.fixture(autouse=True)
def _fresh_stop():
    driver.reset_stop()
    yield
    driver.reset_stop()


def _sum_flow(inp, out, stop_at=None):
    """Keyed running-sum flow; when ``stop_at`` is given, a host-tier
    map step requests a graceful stop the moment that input value
    passes through — a deterministic in-band stand-in for SIGTERM."""
    flow = Dataflow("graceful_df")
    s = op.input("inp", flow, TestingSource(inp, batch_size=4))

    def trig(kv, _stop_at=stop_at):
        if _stop_at is not None and kv[1] == _stop_at:
            request_stop()
        return kv

    s = op.map("trig", s, trig)
    s = op.stateful_map(
        "sum", s, lambda st, v: ((st or 0.0) + v,) * 2
    )
    op.output("out", s, TestingSink(out))
    return flow


def _oracle(rows):
    sums, want = {}, []
    for k, v in rows:
        sums[k] = sums.get(k, 0.0) + v
        want.append((k, sums[k]))
    return want


def test_graceful_stop_commits_and_resumes_with_zero_replay(
    tmp_path, entry_point
):
    # Mid-stream stop under every entry point (single lane and the
    # in-process cluster lanes): the stop epoch commits, the status
    # is typed, and the resumed execution starts at exactly the next
    # epoch — zero replay — with exactly-once output overall.
    inp = [(f"k{i % 8}", float(i)) for i in range(600)]
    db = tmp_path / "db"
    db.mkdir()
    init_db_dir(db, 2)
    rc = RecoveryConfig(str(db))

    stops_before = flight.RECORDER.counters.get(
        "graceful_stop_count", 0
    )
    out = []
    status = entry_point(
        _sum_flow(inp, out, stop_at=300.0),
        epoch_interval=ZERO_TD,
        recovery_config=rc,
    )
    assert isinstance(status, GracefulStop)
    assert (
        flight.RECORDER.counters.get("graceful_stop_count", 0)
        == stops_before + 1
    )
    n = len(out)
    assert 0 < n < len(inp), "stop should land mid-stream"
    # Every consumed row's output landed (keyed batches group per
    # key, so compare multisets — each running-sum pair is unique).
    assert sorted(out) == sorted(_oracle(inp)[:n])

    # Zero replayed epochs: the resume point is exactly one past the
    # epoch the graceful stop committed.
    store = RecoveryStore(rc.db_dir)
    resume = store.resume_from()
    store.close()
    assert resume.resume_epoch == status.epoch + 1

    # The resumed execution finishes the stream exactly-once.
    out2 = []
    status2 = entry_point(
        _sum_flow(inp, out2),
        epoch_interval=ZERO_TD,
        recovery_config=rc,
    )
    assert status2 is None
    assert sorted(out + out2) == sorted(_oracle(inp))


def test_stop_request_before_run_is_honored_then_consumed():
    # A stop requested BEFORE the entry point (the k8s SIGTERM-
    # during-slow-import shape, or an embedder calling request_stop
    # just before run_main) must stop that execution at its first
    # epoch close...
    request_stop()
    inp = [(f"k{i % 2}", float(i)) for i in range(64)]
    out = []
    status = run_main(_sum_flow(inp, out), epoch_interval=ZERO_TD)
    assert isinstance(status, GracefulStop)
    assert len(out) < len(inp)
    # ...and the request is consumed when the invocation ends: the
    # next execution runs to EOF (a stop targets one execution, not
    # the process forever).
    out2 = []
    status2 = run_main(_sum_flow(inp, out2), epoch_interval=ZERO_TD)
    assert status2 is None
    assert sorted(out2) == sorted(_oracle(inp))


def test_health_and_status_report_draining():
    flow = _sum_flow([("a", 1.0)], [])
    d = driver._Driver(
        flow,
        worker_count=1,
        epoch_interval=ZERO_TD,
        recovery_config=None,
    )
    h = d._health()
    assert h["state"] == "starting"
    assert not h["ready"] and not h["draining"]
    d._ready = True
    h = d._health()
    assert h["ready"] and h["state"] == "ready"

    request_stop()
    h = d._health()
    assert h["state"] == "draining"
    assert h["draining"] and not h["ready"]
    st = d._status()
    assert st["stopping"] is True
    # The hint exposes the advice history list for K-consecutive
    # hysteresis consumers (empty before any epoch close).
    assert st["rescale_hint"]["history"] == []


def test_rescale_hint_history_recorded_at_epoch_close():
    inp = [(f"k{i % 4}", float(i)) for i in range(64)]
    out = []
    d = driver._Driver(
        _sum_flow(inp, out),
        worker_count=1,
        epoch_interval=ZERO_TD,
        recovery_config=None,
    )
    assert d.run() is None
    history = d._rescale_hint()["history"]
    assert history, "epoch closes should record advice samples"
    for sample in history:
        assert sample["advice"] in ("grow", "shrink", "hold")
        assert sample["epoch"] >= 1
    # Rate limited to one sample per second: a sub-second run with
    # hundreds of interval-0 closes records just the first.
    assert len(history) == 1


def test_webserver_stop_endpoint_and_draining(tmp_path, monkeypatch):
    # Unit test of the API-plane surfaces: POST /stop arms the stop
    # flag, /healthz flips to 503 + draining, /status reports
    # stopping — with fake fns, no engine run.
    monkeypatch.chdir(tmp_path)  # the server dumps dataflow.json
    monkeypatch.setenv("BYTEWAX_DATAFLOW_API_ENABLED", "1")
    monkeypatch.setenv("BYTEWAX_DATAFLOW_API_PORT", "0")
    from bytewax_tpu.engine.webserver import maybe_start_server

    state = {"stop": False}

    def health():
        draining = state["stop"]
        return {
            "ready": not draining,
            "draining": draining,
            "state": "draining" if draining else "ready",
        }

    srv = maybe_start_server(
        _sum_flow([("a", 1.0)], []),
        status_fn=lambda: {"stopping": state["stop"]},
        health_fn=health,
        stop_fn=lambda: state.__setitem__("stop", True),
    )
    assert srv is not None
    base = f"http://127.0.0.1:{srv.port}"
    try:
        with urllib.request.urlopen(base + "/healthz", timeout=5) as rsp:
            body = json.loads(rsp.read())
        assert body["ready"] and not body["draining"]

        req = urllib.request.Request(
            base + "/stop", data=b"", method="POST"
        )
        with urllib.request.urlopen(req, timeout=5) as rsp:
            assert json.loads(rsp.read())["stopping"] is True

        with pytest.raises(urllib.error.HTTPError) as exc_info:
            urllib.request.urlopen(base + "/healthz", timeout=5)
        assert exc_info.value.code == 503
        body = json.loads(exc_info.value.read())
        assert body["draining"] and body["state"] == "draining"
        assert body["live"], "liveness must stay green while draining"

        with urllib.request.urlopen(base + "/status", timeout=5) as rsp:
            assert json.loads(rsp.read())["stopping"] is True

        # POST anywhere else stays a 404 (no new surface).
        req = urllib.request.Request(
            base + "/nope", data=b"", method="POST"
        )
        with pytest.raises(urllib.error.HTTPError) as exc_info:
            urllib.request.urlopen(req, timeout=5)
        assert exc_info.value.code == 404
    finally:
        srv.shutdown()


def test_webserver_reconfigure_endpoint(tmp_path, monkeypatch):
    # POST /reconfigure (docs/recovery.md "Live partial rescale")
    # records the pending membership target; malformed bodies are a
    # 400, not a 500 (the plane never dies), and without a
    # reconfigure_fn the path stays a 404.
    monkeypatch.chdir(tmp_path)
    monkeypatch.setenv("BYTEWAX_DATAFLOW_API_ENABLED", "1")
    monkeypatch.setenv("BYTEWAX_DATAFLOW_API_PORT", "0")
    from bytewax_tpu.engine.webserver import maybe_start_server

    got = []
    srv = maybe_start_server(
        _sum_flow([("a", 1.0)], []),
        reconfigure_fn=lambda addrs, wpp: got.append((addrs, wpp)),
    )
    assert srv is not None
    base = f"http://127.0.0.1:{srv.port}"
    try:
        body = json.dumps(
            {
                "addresses": ["127.0.0.1:9001", "127.0.0.1:9002"],
                "workers_per_process": 2,
            }
        ).encode()
        req = urllib.request.Request(
            base + "/reconfigure", data=body, method="POST"
        )
        with urllib.request.urlopen(req, timeout=5) as rsp:
            assert json.loads(rsp.read())["reconfiguring"] is True
        assert got == [(["127.0.0.1:9001", "127.0.0.1:9002"], 2)]

        req = urllib.request.Request(
            base + "/reconfigure",
            data=json.dumps({"addresses": "nope"}).encode(),
            method="POST",
        )
        with pytest.raises(urllib.error.HTTPError) as exc_info:
            urllib.request.urlopen(req, timeout=5)
        assert exc_info.value.code == 400
        assert len(got) == 1  # the bad body recorded nothing
    finally:
        srv.shutdown()

    srv = maybe_start_server(_sum_flow([("a", 1.0)], []))
    assert srv is not None
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/reconfigure",
            data=b"{}",
            method="POST",
        )
        with pytest.raises(urllib.error.HTTPError) as exc_info:
            urllib.request.urlopen(req, timeout=5)
        assert exc_info.value.code == 404
    finally:
        srv.shutdown()


def test_health_reports_migrating_during_pending_rescale(tmp_path):
    # The /healthz `migrating` state (docs/recovery.md "Live partial
    # rescale"): a driver built against a store written at a
    # different worker count reports state=migrating (not a bare
    # starting/503) until the startup migration completes — external
    # supervisors must read it as live progress.  Built through the
    # REAL resume path: a run at 2 lanes populates the store, then a
    # driver at 3 lanes with rescale forced on is constructed (the
    # construction computes the rescale view; run() would migrate).
    from bytewax_tpu.engine.driver import _Driver, cluster_main

    db = tmp_path / "db"
    db.mkdir()
    init_db_dir(db, 1)
    inp = [(f"k{i % 4}", float(i)) for i in range(16)]
    cluster_main(
        _sum_flow(inp, []),
        [],
        0,
        worker_count_per_proc=2,
        epoch_interval=ZERO_TD,
        recovery_config=RecoveryConfig(str(db)),
    )
    drv = _Driver(
        _sum_flow(inp, []),
        worker_count=3,
        epoch_interval=ZERO_TD,
        recovery_config=RecoveryConfig(str(db)),
        force_rescale=True,
    )
    try:
        health = drv._health()
        assert health["state"] == "migrating"
        assert health["ready"] is False
        assert drv._migrating is True
    finally:
        drv.store.close()


def test_webserver_remote_stop_requires_opt_in(tmp_path, monkeypatch):
    # POST /stop is the plane's one mutating endpoint: on a
    # non-loopback bind (the k8s probe-wiring case) it is disabled
    # unless BYTEWAX_TPU_ALLOW_REMOTE_STOP=1 — any network peer
    # could otherwise drain the whole cluster.
    monkeypatch.chdir(tmp_path)
    monkeypatch.setenv("BYTEWAX_DATAFLOW_API_ENABLED", "1")
    monkeypatch.setenv("BYTEWAX_DATAFLOW_API_PORT", "0")
    monkeypatch.setenv("BYTEWAX_DATAFLOW_API_HOST", "0.0.0.0")
    from bytewax_tpu.engine.webserver import maybe_start_server

    state = {"stop": False}
    flow = _sum_flow([("a", 1.0)], [])
    srv = maybe_start_server(
        flow, stop_fn=lambda: state.__setitem__("stop", True)
    )
    assert srv is not None
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/stop",
            data=b"",
            method="POST",
        )
        with pytest.raises(urllib.error.HTTPError) as exc_info:
            urllib.request.urlopen(req, timeout=5)
        assert exc_info.value.code == 404
        assert state["stop"] is False
    finally:
        srv.shutdown()

    monkeypatch.setenv("BYTEWAX_TPU_ALLOW_REMOTE_STOP", "1")
    srv = maybe_start_server(
        flow, stop_fn=lambda: state.__setitem__("stop", True)
    )
    assert srv is not None
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/stop",
            data=b"",
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=5) as rsp:
            assert json.loads(rsp.read())["stopping"] is True
        assert state["stop"] is True
    finally:
        srv.shutdown()
