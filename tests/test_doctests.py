"""Run the runnable examples embedded in docstrings (the analog of the
reference's sphinx `{testcode}` doctests, SURVEY.md §4 item 8)."""

import doctest

import pytest

MODULES = [
    "bytewax_tpu.dataflow",
    "bytewax_tpu.operators",
    "bytewax_tpu.operators.helpers",
    "bytewax_tpu.operators.windowing",
    "bytewax_tpu.engine.arrays",
    "bytewax_tpu.engine.backoff",
    "bytewax_tpu.inputs",
    "bytewax_tpu.outputs",
    "bytewax_tpu.xla",
    "bytewax_tpu.errors",
    "bytewax_tpu.connectors.demo",
    "bytewax_tpu.connectors.files",
    "bytewax_tpu.connectors.kafka",
    "bytewax_tpu.connectors.stdio",
    "bytewax_tpu.recovery",
    "bytewax_tpu.testing",
    "bytewax_tpu.tracing",
    "bytewax_tpu.visualize",
]


@pytest.mark.parametrize("modname", MODULES)
def test_module_doctests(modname):
    import importlib

    mod = importlib.import_module(modname)
    results = doctest.testmod(
        mod, verbose=False, optionflags=doctest.NORMALIZE_WHITESPACE
    )
    assert results.failed == 0, f"{results.failed} doctest failures"


def test_doctest_examples_exist():
    # The operator library must actually carry runnable examples.
    import importlib

    mod = importlib.import_module("bytewax_tpu.operators")
    finder = doctest.DocTestFinder()
    tests = [t for t in finder.find(mod) if t.examples]
    assert len(tests) >= 20, f"only {len(tests)} operators carry examples"


def test_every_public_operator_has_example():
    """Every public operator function (the `@operator`-decorated API in
    `operators/` modules) carries a runnable docstring example, matching
    the reference's every-docstring `{testcode}` policy (SURVEY §4 item
    8)."""
    import importlib
    import inspect

    finder = doctest.DocTestFinder()
    missing = []
    for modname in [
        "bytewax_tpu.operators",
        "bytewax_tpu.operators.helpers",
        "bytewax_tpu.operators.windowing",
    ]:
        mod = importlib.import_module(modname)
        for name in dir(mod):
            if name.startswith("_"):
                continue
            obj = getattr(mod, name)
            if not inspect.isfunction(obj):
                continue
            if getattr(obj, "__module__", None) != modname:
                continue
            if not [t for t in finder.find(obj, name=name) if t.examples]:
                missing.append(f"{modname}.{name}")
    assert not missing, f"public operators without examples: {missing}"


def test_every_connector_has_example():
    """Every public connector class carries a runnable docstring
    example (broker-backed Kafka source/sink classes document their
    message types instead; their IO needs a live broker)."""
    import importlib

    finder = doctest.DocTestFinder()
    targets = {
        "bytewax_tpu.connectors.files": [
            "CSVSource", "DirSink", "DirSource", "FileSink", "FileSource",
        ],
        "bytewax_tpu.connectors.stdio": ["StdOutSink"],
        "bytewax_tpu.connectors.demo": ["RandomMetricSource"],
        "bytewax_tpu.connectors.kafka": [
            "KafkaError", "KafkaSinkMessage", "KafkaSourceMessage",
        ],
    }
    missing = []
    for modname, names in targets.items():
        mod = importlib.import_module(modname)
        for name in names:
            obj = getattr(mod, name)
            if not [t for t in finder.find(obj, name=name) if t.examples]:
                missing.append(f"{modname}.{name}")
    assert not missing, f"connectors without examples: {missing}"
