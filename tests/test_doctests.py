"""Run the runnable examples embedded in docstrings (the analog of the
reference's sphinx `{testcode}` doctests, SURVEY.md §4 item 8)."""

import doctest

import pytest

MODULES = [
    "bytewax_tpu.dataflow",
    "bytewax_tpu.operators",
    "bytewax_tpu.operators.helpers",
    "bytewax_tpu.operators.windowing",
    "bytewax_tpu.engine.arrays",
    "bytewax_tpu.inputs",
    "bytewax_tpu.outputs",
    "bytewax_tpu.xla",
]


@pytest.mark.parametrize("modname", MODULES)
def test_module_doctests(modname):
    import importlib

    mod = importlib.import_module(modname)
    results = doctest.testmod(
        mod, verbose=False, optionflags=doctest.NORMALIZE_WHITESPACE
    )
    assert results.failed == 0, f"{results.failed} doctest failures"


def test_doctest_examples_exist():
    # The operator library must actually carry runnable examples.
    import importlib

    mod = importlib.import_module("bytewax_tpu.operators")
    finder = doctest.DocTestFinder()
    tests = [t for t in finder.find(mod) if t.examples]
    assert len(tests) >= 20, f"only {len(tests)} operators carry examples"
