"""The asynchronous device-dispatch pipeline (engine/pipeline.py).

Covers the drain-point contracts docs/performance.md documents:
flush-before-snapshot (cross-tier recovery stays exact at depth ≥ 2),
the chaos path (a mid-pipeline :class:`DeviceFault` through the real
``device_dispatch`` fault site retries, then demotes with state
continuity — no engine internals monkeypatched), depth-1 equivalence
with the deferred depths, and the ``DevicePipeline`` primitive itself
(ordering, bounding, error propagation).
"""

import os
from datetime import datetime, timedelta, timezone

import pytest

import bytewax_tpu.operators as op
import bytewax_tpu.operators.windowing as w
from bytewax_tpu import xla
from bytewax_tpu.dataflow import Dataflow
from bytewax_tpu.engine import faults, flight
from bytewax_tpu.engine.pipeline import DevicePipeline, pipeline_depth
from bytewax_tpu.operators.windowing import EventClock, TumblingWindower
from bytewax_tpu.testing import TestingSink, TestingSource, run_main

ZERO_TD = timedelta(seconds=0)
ALIGN = datetime(2022, 1, 1, tzinfo=timezone.utc)


@pytest.fixture(autouse=True)
def _fresh_injector():
    faults.reset()
    yield
    faults.reset()


# -- the DevicePipeline primitive ---------------------------------------


def test_pipeline_depth_env(monkeypatch):
    monkeypatch.delenv("BYTEWAX_TPU_PIPELINE_DEPTH", raising=False)
    assert pipeline_depth() == 2
    monkeypatch.setenv("BYTEWAX_TPU_PIPELINE_DEPTH", "4")
    assert pipeline_depth() == 4
    monkeypatch.setenv("BYTEWAX_TPU_PIPELINE_DEPTH", "0")
    assert pipeline_depth() == 1  # floor: depth 1 == synchronous
    monkeypatch.setenv("BYTEWAX_TPU_PIPELINE_DEPTH", "nope")
    with pytest.raises(ValueError, match="PIPELINE_DEPTH"):
        pipeline_depth()


def test_pipeline_finalizes_in_submission_order():
    pipe = DevicePipeline("s", depth=3)
    done = []
    try:
        for i in range(6):
            pipe.push(lambda i=i: i, lambda r: done.append(r))
        pipe.flush()
    finally:
        pipe.shutdown()
    assert done == [0, 1, 2, 3, 4, 5]


def test_pipeline_bounds_in_flight_work():
    pipe = DevicePipeline("s", depth=2)
    done = []
    try:
        pipe.push(lambda: "a", done.append)
        # Depth 2 = one pending: pushing the second finalizes the
        # first BEFORE the new task is enqueued (the fallback-ordering
        # invariant _dispatch_device relies on).
        pipe.push(lambda: "b", done.append)
        assert done == ["a"]
        assert len(pipe) == 1
        pipe.flush()
    finally:
        pipe.shutdown()
    assert done == ["a", "b"]


def test_pipeline_depth1_runs_inline():
    pipe = DevicePipeline("s", depth=1)
    done = []
    pipe.push(lambda: "now", done.append)
    assert done == ["now"]
    assert not pipe.pending()
    pipe.shutdown()  # no worker was ever created


def test_pipeline_task_error_surfaces_at_drain():
    pipe = DevicePipeline("s", depth=2)

    def boom():
        raise RuntimeError("device phase failed")

    try:
        pipe.push(boom, lambda r: None)
        with pytest.raises(RuntimeError, match="device phase failed"):
            pipe.flush()
        assert not pipe.pending()  # the failed task was dropped
    finally:
        pipe.shutdown()


# -- flush-before-snapshot: cross-tier recovery at depth >= 2 -----------


def _scan_flow(inp, out):
    flow = Dataflow("pipe_scan_df")
    s = op.input("inp", flow, TestingSource(inp, batch_size=2))
    s = op.stateful_map("scan", s, xla.ema(0.5))
    op.output("out", s, TestingSink(out))
    return flow


def test_flush_before_snapshot_cross_tier_recovery(
    entry_point, recovery_config, monkeypatch
):
    """At depth ≥ 2 the device tier defers emissions into the
    pipeline; every epoch close must drain them first, or the resumed
    execution would double- or under-emit.  Abort mid-stream on the
    device tier, resume on the HOST tier (cross-tier snapshot
    interchange), and require exactly-once end to end — under every
    entry point."""
    monkeypatch.setenv("BYTEWAX_TPU_PIPELINE_DEPTH", "3")
    items = [("a", 1.0), ("a", 2.0), ("b", 5.0), ("a", 4.0)]
    tail = [("a", 3.0), ("b", 6.0)]
    inp = items + [TestingSource.ABORT()] + tail

    out1 = []
    entry_point(
        _scan_flow(inp, out1),
        epoch_interval=ZERO_TD,
        recovery_config=recovery_config,
    )
    out2 = []
    monkeypatch.setenv("BYTEWAX_TPU_ACCEL", "0")
    entry_point(
        _scan_flow(inp, out2),
        epoch_interval=ZERO_TD,
        recovery_config=recovery_config,
    )
    # Exactly-once across the abort and the tier switch: every input
    # row produced exactly one output row, in stream order per key
    # (multi-lane entry points may interleave keys between lanes).
    def per_key(rows):
        by = {}
        for k, v in rows:
            by.setdefault(k, []).append(v)
        return by

    got = per_key(out1 + out2)
    want = per_key(items + tail)
    assert {k: [v for v, _e in vs] for k, vs in got.items()} == want
    # And the resumed EMA continued from the device tier's state, not
    # from scratch: the whole two-run stream must match an unbroken
    # host-tier oracle over the full input (no abort, no recovery).
    oracle_out = []
    run_main(
        _scan_flow(items + tail, oracle_out), epoch_interval=ZERO_TD
    )
    oracle = per_key(oracle_out)
    for key, rows in oracle.items():
        for (gv, ge), (ov, oe) in zip(got[key], rows):
            assert gv == ov
            assert ge == pytest.approx(oe, abs=1e-4)


def test_windowed_outputs_identical_across_depths(monkeypatch):
    """Depth 1 (synchronous — the pre-pipeline engine) and deferred
    depths must produce identical event streams, including late
    events and window metadata order."""

    def run_at(depth):
        monkeypatch.setenv("BYTEWAX_TPU_PIPELINE_DEPTH", str(depth))
        n = 300
        inp = [
            (ALIGN + timedelta(seconds=(i * 7) % 120), f"k{i % 3}")
            for i in range(n)
        ]
        out = []
        flow = Dataflow("pipe_depth_df")
        s = op.input("inp", flow, TestingSource(inp, batch_size=16))
        clock = EventClock(
            ts_getter=lambda item: item[0],
            wait_for_system_duration=ZERO_TD,
        )
        windower = TumblingWindower(
            length=timedelta(minutes=1), align_to=ALIGN
        )
        wo = w.count_window(
            "count", s, clock, windower, key=lambda item: item[1]
        )
        op.output("out", wo.down, TestingSink(out))
        run_main(flow, epoch_interval=ZERO_TD)
        return out

    assert run_at(1) == run_at(2) == run_at(4)


# -- chaos: mid-pipeline DeviceFault retries then demotes ---------------


def test_mid_pipeline_device_fault_retries_then_demotes(monkeypatch):
    """With deliveries in flight at depth ≥ 2, injected
    ``device_dispatch`` faults (the real faults.py site — no
    monkeypatched engine internals) first retry in place, then demote
    the step to the host tier; the demotion drains the pipeline
    first, so totals stay exact across the tier switch."""
    monkeypatch.setenv("BYTEWAX_TPU_PIPELINE_DEPTH", "3")
    monkeypatch.setenv("BYTEWAX_TPU_FAULTS", "device_dispatch:error:2+")
    monkeypatch.setenv("BYTEWAX_TPU_DEMOTE_AFTER", "3")
    monkeypatch.setenv("BYTEWAX_FLIGHT_RECORDER", "1")
    # The epoch-2+ fault schedule needs deliveries spread across
    # epochs; keep ingest at source batch granularity.
    monkeypatch.setenv("BYTEWAX_TPU_INGEST_TARGET_ROWS", "0")

    n = 48
    inp = [(f"k{i % 4}", 1.0) for i in range(n)]
    out = []
    flow = Dataflow("pipe_demote_df")
    s = op.input("inp", flow, TestingSource(inp, batch_size=4))
    r = op.reduce_final("sum", s, xla.SUM)
    op.output("out", r, TestingSink(out))

    run_main(flow, epoch_interval=ZERO_TD)

    # State continuity: epoch-1 device folds + post-demotion host
    # folds add up to every row exactly once.
    assert dict(out) == {f"k{i}": n / 4 for i in range(4)}
    demotions = [
        e for e in flight.RECORDER.tail() if e["kind"] == "demotion"
    ]
    assert demotions and demotions[-1]["step"].startswith(
        "pipe_demote_df.sum"
    )
    assert flight.RECORDER.counters.get("fault_injected_count", 0) >= 3


# -- observability ------------------------------------------------------


def test_pipeline_metrics_exposed(monkeypatch):
    monkeypatch.setenv("BYTEWAX_TPU_PIPELINE_DEPTH", "2")
    inp = [(f"k{i % 2}", float(i)) for i in range(32)]
    out = []
    flow = Dataflow("pipe_metrics_df")
    s = op.input("inp", flow, TestingSource(inp, batch_size=4))
    r = op.reduce_final("sum", s, xla.SUM)
    op.output("out", r, TestingSink(out))
    run_main(flow)
    assert flight.RECORDER.counters.get("pipeline_depth") == 2
    from bytewax_tpu._metrics import generate_python_metrics

    text = generate_python_metrics()
    assert "bytewax_pipeline_depth" in text
    assert "bytewax_pipeline_flush_stall_seconds" in text


def test_global_exchange_tier_never_enters_dispatch_pipeline(monkeypatch):
    """The collective global-exchange tier never enters the
    per-delivery dispatch pipeline: its flush is a cluster collective
    legal only at globally-ordered points, so the driver never arms a
    ``_pipe`` for it.  (The tier's OWN overlapped exchange lane —
    ``BYTEWAX_TPU_GSYNC_OVERLAP``, default off — is a different,
    deliberately fenced surface: rounds are sealed at epoch close and
    fenced at the next close/finalize, never per batch.)"""
    from bytewax_tpu.engine.pipeline import DevicePipeline as DP

    assert DP.__init__.__defaults__ == (None, "device")
    # Contract is structural: _StatefulBatchRt only builds a pipeline
    # for non-global tiers (see driver.__init__); pin the guard here
    # so a refactor can't silently drop it.
    import inspect

    from bytewax_tpu.engine import driver as drv

    src = inspect.getsource(drv._StatefulBatchRt.__init__)
    assert "global_exchange" in src and "DevicePipeline" in src
    # And with overlap off (the default), the global tier constructs
    # no lane at all — byte-identical to the lock-step engine.
    from bytewax_tpu.engine import sharded_state as ss

    monkeypatch.delenv("BYTEWAX_TPU_GSYNC_OVERLAP", raising=False)
    assert ss._gsync_overlap() is False
