"""Exporter-path tracing tests: a local stub collector receives real
OTLP/HTTP+JSON ``ExportTraceServiceRequest`` documents from the
built-in exporter (reference surface:
``/root/reference/src/tracing/otlp_tracing.rs:38-96``) — service
name, span names/attributes, trace ancestry, and sampling are
asserted on the wire, not on internals."""

import http.server
import json
import threading

import pytest

import bytewax_tpu.tracing as tracing
from bytewax_tpu.tracing import (
    JaegerConfig,
    OtlpTracingConfig,
    setup_tracing,
    span,
    spans_active,
)


class _Collector:
    """Minimal OTLP/HTTP collector: records every POST /v1/traces."""

    def __init__(self):
        self.requests = []
        outer = self

        class _Handler(http.server.BaseHTTPRequestHandler):
            def do_POST(self):
                length = int(self.headers["Content-Length"])
                outer.requests.append(
                    (self.path, json.loads(self.rfile.read(length)))
                )
                body = b"{}"
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):
                pass

        self._srv = http.server.HTTPServer(("127.0.0.1", 0), _Handler)
        self._thread = threading.Thread(
            target=self._srv.serve_forever, daemon=True
        )
        self._thread.start()
        self.url = f"http://127.0.0.1:{self._srv.server_address[1]}"

    def spans(self):
        out = []
        for _path, doc in self.requests:
            for rs in doc["resourceSpans"]:
                service = next(
                    a["value"]["stringValue"]
                    for a in rs["resource"]["attributes"]
                    if a["key"] == "service.name"
                )
                for ss in rs["scopeSpans"]:
                    for sp in ss["spans"]:
                        out.append((service, sp))
        return out

    def close(self):
        self._srv.shutdown()
        self._srv.server_close()


@pytest.fixture
def collector():
    c = _Collector()
    prev = tracing._tracer
    yield c
    c.close()
    if tracing._tracer is not None:
        tracing._tracer.shutdown()
    tracing._tracer = prev


def _attrs(sp):
    return {
        a["key"]: a["value"]["stringValue"]
        for a in sp.get("attributes", [])
    }


def test_otlp_http_export_service_and_attrs(collector):
    guard = setup_tracing(
        OtlpTracingConfig(service_name="svc-under-test", url=collector.url)
    )
    assert spans_active()
    with span("epoch_close", epoch=3):
        with span("operator", step_id="df.map"):
            pass
    guard.shutdown()

    got = collector.spans()
    assert got, "no spans reached the collector"
    services = {svc for svc, _sp in got}
    assert services == {"svc-under-test"}
    by_name = {sp["name"]: sp for _svc, sp in got}
    assert set(by_name) == {"epoch_close", "operator"}
    assert _attrs(by_name["epoch_close"])["epoch"] == "3"
    assert _attrs(by_name["operator"])["step_id"] == "df.map"
    # Ancestry: the child carries the root's trace id + span id.
    root = by_name["epoch_close"]
    child = by_name["operator"]
    assert child["traceId"] == root["traceId"]
    assert child["parentSpanId"] == root["spanId"]
    assert "parentSpanId" not in root
    # Timestamps are plausible nanos.
    assert int(root["endTimeUnixNano"]) >= int(root["startTimeUnixNano"])


def test_jaeger_config_otlp_http(collector):
    # Jaeger >=1.35 ingests OTLP natively; JaegerConfig with an http
    # endpoint rides the same built-in transport.
    guard = setup_tracing(
        JaegerConfig(service_name="jaeger-svc", endpoint=collector.url)
    )
    with span("flush"):
        pass
    guard.shutdown()
    got = collector.spans()
    assert {svc for svc, _sp in got} == {"jaeger-svc"}
    assert [sp["name"] for _svc, sp in got] == ["flush"]
    assert collector.requests[0][0] == "/v1/traces"


def test_sampling_ratio_zero_drops_all(collector):
    guard = setup_tracing(
        OtlpTracingConfig(
            service_name="svc", url=collector.url, sampling_ratio=0.0
        )
    )
    for _ in range(20):
        with span("never"):
            pass
    guard.shutdown()
    assert collector.spans() == []


def test_sampling_is_per_trace(collector):
    # Children inherit the root's decision: traces arrive whole.
    guard = setup_tracing(
        OtlpTracingConfig(
            service_name="svc", url=collector.url, sampling_ratio=0.5
        )
    )
    for _ in range(40):
        with span("root"):
            with span("child"):
                pass
    guard.shutdown()
    got = collector.spans()
    roots = [sp for _s, sp in got if sp["name"] == "root"]
    children = [sp for _s, sp in got if sp["name"] == "child"]
    assert len(roots) == len(children)
    root_traces = {sp["traceId"] for sp in roots}
    assert all(sp["traceId"] in root_traces for sp in children)
    # ~50% sampled; bound loosely (p < 1e-6 to flake).
    assert 5 <= len(roots) <= 35


def test_dataflow_emits_operator_spans(collector):
    """End-to-end: a real dataflow run with an exporting backend
    produces engine spans (epoch_close + per-operator activations)
    at the collector."""
    import bytewax_tpu.operators as op
    from bytewax_tpu.dataflow import Dataflow
    from bytewax_tpu.testing import TestingSink, TestingSource, run_main

    guard = setup_tracing(
        OtlpTracingConfig(service_name="df-svc", url=collector.url)
    )
    out = []
    flow = Dataflow("traced")
    s = op.input("inp", flow, TestingSource([1, 2, 3]))
    s = op.map("double", s, lambda x: x * 2)
    op.output("out", s, TestingSink(out))
    run_main(flow)
    guard.shutdown()

    assert out == [2, 4, 6]
    names = {sp["name"] for _svc, sp in collector.spans()}
    assert "epoch_close" in names
    assert "operator" in names
    step_ids = {
        _attrs(sp).get("step_id")
        for _svc, sp in collector.spans()
        if sp["name"] == "operator"
    }
    assert "traced.double.flat_map_batch" in step_ids or any(
        s and "double" in s for s in step_ids
    )


def test_grpc_url_without_sdk_raises_clearly():
    try:
        import opentelemetry.sdk  # noqa: F401

        pytest.skip("opentelemetry-sdk installed")
    except ImportError:
        pass
    with pytest.raises(ImportError, match="http"):
        setup_tracing(
            OtlpTracingConfig(
                service_name="svc", url="grpc://127.0.0.1:4317"
            )
        )
