"""Tier-1 wrapper around the engine-contract analyzer: the shipped
tree must be clean (golden test), via both the API and the CLI entry
points (``python -m bytewax_tpu.analysis`` is what CI and operators
run)."""

import re
import subprocess
import sys
import time
from pathlib import Path

from bytewax_tpu.analysis import analyze_tree
from bytewax_tpu.analysis.contracts import KNOBS
from bytewax_tpu.analysis.diagnostics import format_diagnostics
from bytewax_tpu.analysis.rules import ALL_RULES

REPO = Path(__file__).resolve().parent.parent


def test_tree_is_clean():
    timings = {}
    t0 = time.perf_counter()
    diags, suppressed, project = analyze_tree(timings=timings)
    wall = time.perf_counter() - t0
    assert not diags, (
        "the shipped tree violates an engine contract (see "
        "docs/contracts.md):\n" + format_diagnostics(diags)
    )
    # The committed baseline is empty: nothing should be suppressed.
    assert suppressed == 0
    # Sanity: the scan actually covered the engine and the examples.
    assert "bytewax_tpu.engine.driver" in project.modules
    assert any(m.startswith("examples.") for m in project.modules)
    # Every rule really ran, and the full tree stays fast enough to
    # run on every CI round (budget well above the ~3s measured, far
    # below the ~5s ceiling the analyzer tooling targets).
    assert set(timings) == set(ALL_RULES) | {"<call-graph>"}
    assert wall < 30, f"analyzer took {wall:.1f}s on the tree"


def test_rule_registry_is_complete():
    assert set(ALL_RULES) == {
        "BTX-SEND",
        "BTX-GSYNC",
        "BTX-FRAMES",
        "BTX-FAULT",
        "BTX-SNAPSHOT",
        "BTX-BACKEND",
        "BTX-DRAIN",
        "BTX-THREAD",
        "BTX-KNOB",
        "BTX-LANE",
        "BTX-RACE",
    }


def test_docs_rule_catalog_matches_registry():
    """docs/contracts.md's rule-catalog table lists exactly the
    analyzer's rule ids — a rule without a catalog entry (or a
    catalog row for a deleted rule) is doc drift, failed here."""
    text = (REPO / "docs" / "contracts.md").read_text()
    catalog = text.split("## Rule catalog", 1)[1].split("##", 1)[0]
    documented = set(
        re.findall(r"^\|\s*`(BTX-[A-Z]+)`", catalog, re.MULTILINE)
    )
    assert documented == set(ALL_RULES), (
        "docs/contracts.md rule catalog drifted from the registry: "
        f"doc-only {sorted(documented - set(ALL_RULES))}, "
        f"undocumented {sorted(set(ALL_RULES) - documented)}"
    )


def test_docs_knob_table_matches_catalog():
    """docs/configuration.md's reference table lists exactly the
    pinned KNOBS catalog (names AND defaults) — the table is
    generated from the catalog and must not drift."""
    text = (REPO / "docs" / "configuration.md").read_text()
    rows = dict(
        re.findall(
            r"^\|\s*`(BYTEWAX_TPU_[A-Z0-9_]+)`\s*\|\s*(?:`([^`|]*)`)?\s*\|",
            text,
            re.MULTILINE,
        )
    )
    assert set(rows) == set(KNOBS), (
        "docs/configuration.md knob table drifted from "
        "contracts.KNOBS: doc-only "
        f"{sorted(set(rows) - set(KNOBS))}, missing "
        f"{sorted(set(KNOBS) - set(rows))}"
    )
    for name, (default, _doc) in KNOBS.items():
        assert rows[name] == default, (
            f"{name}: doc default {rows[name]!r} != catalog "
            f"{default!r}"
        )


def test_docs_metrics_inventory_matches_registry():
    """docs/observability.md's metrics inventory lists exactly the
    Prometheus families ``_metrics.py`` registers — same
    update-both-together rule as KNOBS ↔ configuration.md: adding a
    family means adding its doc row in the same change (and vice
    versa)."""
    from prometheus_client import Counter, Gauge, Histogram

    import bytewax_tpu._metrics as _metrics

    registered = {
        m._name
        for m in vars(_metrics).values()
        if isinstance(m, (Counter, Gauge, Histogram))
    }
    registered |= {
        h._name for h in _metrics.DURATION_HISTOGRAMS.values()
    }

    text = (REPO / "docs" / "observability.md").read_text()
    inventory = text.split("## Metrics inventory", 1)[1].split(
        "\n## ", 1
    )[0]
    documented = set(
        re.findall(r"`(bytewax_[a-z0-9_]+)`", inventory)
    )
    assert documented == registered, (
        "docs/observability.md metrics inventory drifted from "
        "_metrics.py: doc-only "
        f"{sorted(documented - registered)}, undocumented "
        f"{sorted(registered - documented)}"
    )


def test_cli_exits_zero_on_shipped_tree():
    res = subprocess.run(
        [sys.executable, "-m", "bytewax_tpu.analysis"],
        capture_output=True,
        text=True,
        cwd=REPO,
        timeout=120,
    )
    assert res.returncode == 0, res.stdout + res.stderr
    assert "clean" in res.stderr


def test_cli_sarif_full_tree_smoke():
    """CI smoke (satellite of the HBM-resident-aggregate PR): the
    code-scanning upload path — ``--output sarif`` over the FULL
    shipped tree (fixtures only exercised it before) — emits one
    valid SARIF 2.1.0 document: all 11 rules in the driver inventory,
    zero results (the tree is clean), exit 0."""
    import json

    res = subprocess.run(
        [
            sys.executable,
            "-m",
            "bytewax_tpu.analysis",
            "--output",
            "sarif",
        ],
        capture_output=True,
        text=True,
        cwd=REPO,
        timeout=120,
    )
    assert res.returncode == 0, res.stdout + res.stderr
    doc = json.loads(res.stdout)
    assert doc["version"] == "2.1.0"
    assert doc["$schema"].endswith("sarif-schema-2.1.0.json")
    (run,) = doc["runs"]
    assert run["tool"]["driver"]["name"] == "bytewax_tpu.analysis"
    assert {r["id"] for r in run["tool"]["driver"]["rules"]} == set(
        ALL_RULES
    )
    assert run["results"] == []


def test_cli_exits_nonzero_on_positive_fixture():
    fixture = (
        REPO / "tests" / "analysis_fixtures" / "fixture_send_alias.py"
    )
    res = subprocess.run(
        [sys.executable, "-m", "bytewax_tpu.analysis", str(fixture)],
        capture_output=True,
        text=True,
        cwd=REPO,
        timeout=120,
    )
    assert res.returncode == 1, res.stdout + res.stderr
    assert "BTX-SEND" in res.stdout


def test_cli_exits_nonzero_on_each_new_rule_fixture():
    fixtures = REPO / "tests" / "analysis_fixtures"
    for name, rule in (
        ("fixture_drain_per_batch.py", "BTX-DRAIN"),
        ("fixture_thread_worker_send.py", "BTX-THREAD"),
        ("fixture_knob_uncataloged.py", "BTX-KNOB"),
        ("fixture_lane_uncataloged.py", "BTX-LANE"),
        ("fixture_lane_unfenced.py", "BTX-LANE"),
        ("fixture_lane_phase.py", "BTX-LANE"),
        ("fixture_race_alias.py", "BTX-RACE"),
    ):
        res = subprocess.run(
            [
                sys.executable,
                "-m",
                "bytewax_tpu.analysis",
                "--rule",
                rule,
                str(fixtures / name),
            ],
            capture_output=True,
            text=True,
            cwd=REPO,
            timeout=120,
        )
        assert res.returncode == 1, (name, res.stdout, res.stderr)
        assert rule in res.stdout, (name, res.stdout)


def test_cli_rule_filter_json_and_timings():
    """The CI surface: --rule filtering, --json output, --timings
    per-rule wall times."""
    fixture = (
        REPO
        / "tests"
        / "analysis_fixtures"
        / "fixture_knob_uncataloged.py"
    )
    res = subprocess.run(
        [
            sys.executable,
            "-m",
            "bytewax_tpu.analysis",
            "--rule",
            "BTX-KNOB",
            "--json",
            "--timings",
            str(fixture),
        ],
        capture_output=True,
        text=True,
        cwd=REPO,
        timeout=120,
    )
    import json

    assert res.returncode == 1, res.stdout + res.stderr
    records = [
        json.loads(line) for line in res.stdout.strip().splitlines()
    ]
    assert records and all(r["rule"] == "BTX-KNOB" for r in records)
    timing_lines = [
        json.loads(line)
        for line in res.stderr.splitlines()
        if line.startswith("{")
    ]
    assert timing_lines and "BTX-KNOB" in timing_lines[0]["timings_s"]
    # Only the requested rule ran.
    assert "BTX-SEND" not in timing_lines[0]["timings_s"]


def test_cli_sarif_output(tmp_path):
    """--output sarif emits one SARIF 2.1.0 document and composes
    with --rule (rule inventory reflects what ran) and
    --write-baseline (the document is still emitted alongside the
    baseline write)."""
    import json

    fixture = (
        REPO / "tests" / "analysis_fixtures" / "fixture_race_alias.py"
    )
    res = subprocess.run(
        [
            sys.executable,
            "-m",
            "bytewax_tpu.analysis",
            "--rule",
            "BTX-RACE",
            "--output",
            "sarif",
            str(fixture),
        ],
        capture_output=True,
        text=True,
        cwd=REPO,
        timeout=120,
    )
    assert res.returncode == 1, res.stdout + res.stderr
    doc = json.loads(res.stdout)
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "bytewax_tpu.analysis"
    # The rule inventory is what RAN, not what fired.
    assert [r["id"] for r in run["tool"]["driver"]["rules"]] == [
        "BTX-RACE"
    ]
    (result,) = run["results"]
    assert result["ruleId"] == "BTX-RACE"
    assert result["level"] == "error"
    loc = result["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"].endswith(
        "fixture_race_alias.py"
    )
    assert loc["region"]["startLine"] > 0
    # --write-baseline still emits the document (and exits 0).
    baseline = tmp_path / "BASELINE"
    res2 = subprocess.run(
        [
            sys.executable,
            "-m",
            "bytewax_tpu.analysis",
            "--rule",
            "BTX-RACE",
            "--output",
            "sarif",
            "--write-baseline",
            "--baseline",
            str(baseline),
            str(fixture),
        ],
        capture_output=True,
        text=True,
        cwd=REPO,
        timeout=120,
    )
    assert res2.returncode == 0, res2.stdout + res2.stderr
    doc2 = json.loads(res2.stdout)
    assert len(doc2["runs"][0]["results"]) == 1
    assert baseline.exists()
