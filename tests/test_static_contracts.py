"""Tier-1 wrapper around the engine-contract analyzer: the shipped
tree must be clean (golden test), via both the API and the CLI entry
points (``python -m bytewax_tpu.analysis`` is what CI and operators
run)."""

import subprocess
import sys
from pathlib import Path

from bytewax_tpu.analysis import analyze_tree
from bytewax_tpu.analysis.diagnostics import format_diagnostics
from bytewax_tpu.analysis.rules import ALL_RULES

REPO = Path(__file__).resolve().parent.parent


def test_tree_is_clean():
    diags, suppressed, project = analyze_tree()
    assert not diags, (
        "the shipped tree violates an engine contract (see "
        "docs/contracts.md):\n" + format_diagnostics(diags)
    )
    # The committed baseline is empty: nothing should be suppressed.
    assert suppressed == 0
    # Sanity: the scan actually covered the engine and the examples.
    assert "bytewax_tpu.engine.driver" in project.modules
    assert any(m.startswith("examples.") for m in project.modules)


def test_rule_registry_is_complete():
    assert set(ALL_RULES) == {
        "BTX-SEND",
        "BTX-GSYNC",
        "BTX-FRAMES",
        "BTX-FAULT",
        "BTX-SNAPSHOT",
        "BTX-BACKEND",
    }


def test_cli_exits_zero_on_shipped_tree():
    res = subprocess.run(
        [sys.executable, "-m", "bytewax_tpu.analysis"],
        capture_output=True,
        text=True,
        cwd=REPO,
        timeout=120,
    )
    assert res.returncode == 0, res.stdout + res.stderr
    assert "clean" in res.stderr


def test_cli_exits_nonzero_on_positive_fixture():
    fixture = (
        REPO / "tests" / "analysis_fixtures" / "fixture_send_alias.py"
    )
    res = subprocess.run(
        [sys.executable, "-m", "bytewax_tpu.analysis", str(fixture)],
        capture_output=True,
        text=True,
        cwd=REPO,
        timeout=120,
    )
    assert res.returncode == 1, res.stdout + res.stderr
    assert "BTX-SEND" in res.stdout
