"""The analyzer's own machinery: positive fixtures for every rule,
the alias shapes the old regex scan provably missed, inline-waiver
and baseline-file round-trips."""

import re
from pathlib import Path

import pytest

from bytewax_tpu.analysis import analyze_paths
from bytewax_tpu.analysis.diagnostics import (
    Diagnostic,
    Waivers,
    apply_baseline,
    load_baseline,
    write_baseline,
)

FIXTURES = Path(__file__).resolve().parent / "analysis_fixtures"
REPO = FIXTURES.parent.parent


def _diags(name, rules=None, scripts=False):
    diags, _suppressed, _project = analyze_paths(
        [FIXTURES / name],
        scripts=scripts,
        rule_ids=rules,
        rel_root=REPO,
    )
    return diags


# -- one positive fixture per rule ------------------------------------------


def test_send_rule_flags_alias_smuggled_raw_send():
    diags = _diags("fixture_send_alias.py", ["BTX-SEND"])
    assert [d.rule for d in diags] == ["BTX-SEND"]
    assert "raw cluster send" in diags[0].message
    # The shape is provably invisible to the regex scan this analyzer
    # replaced: the old strict matcher required a literal `comm.`
    # receiver on the call line.
    old_regex = re.compile(
        r"(?:\bcomm\s*\.\s*(?:send|broadcast)\s*\()"
        r"|(?:self\s*\.\s*comm\s*\.\s*(?:send|broadcast)\s*\()"
    )
    source = (FIXTURES / "fixture_send_alias.py").read_text()
    assert not old_regex.search(source)


def test_gsync_rule_flags_per_batch_reachability():
    diags = _diags("fixture_gsync_per_batch.py", ["BTX-GSYNC"])
    reach = [d for d in diags if "per-batch path" in d.message]
    assert reach, diags
    assert "EagerExchange.process" in reach[0].message
    assert "_sync_now" in reach[0].message  # witness chain
    # Invisible to the old regex: no line spells `global_sync(` —
    # the primitive hides behind a bound-method alias.
    source = (FIXTURES / "fixture_gsync_per_batch.py").read_text()
    body = "\n".join(
        line
        for line in source.splitlines()
        if not line.lstrip().startswith(("#", '"', "'"))
    )
    assert not re.search(r"global_sync\s*\(", body)


def test_frames_rule_flags_rogue_kind():
    diags = _diags("fixture_frames_rogue.py", ["BTX-FRAMES"])
    msgs = "\n".join(d.message for d in diags)
    assert "rogue_frame" in msgs
    assert any("inventory drifted" in d.message for d in diags)
    assert any("sent in" in d.message for d in diags)


def test_fault_rule_flags_unknown_site_and_late_fire():
    diags = _diags("fixture_fault_site.py", ["BTX-FAULT"])
    msgs = "\n".join(d.message for d in diags)
    assert "device_dispatchx" in msgs
    assert "before firing" in msgs
    # The reachability component: a pre-fire call that only REACHES a
    # mutator through the call graph (the pipeline indirection shape)
    # is flagged with its witness chain.
    assert "_spin_helper" in msgs
    assert "_process_device" in msgs


def test_snapshot_rule_flags_missing_demotion_method():
    diags = _diags("fixture_snapshot_missing.py", ["BTX-SNAPSHOT"])
    assert [d.rule for d in diags] == ["BTX-SNAPSHOT"]
    assert "OrphanDeviceState" in diags[0].message


def test_snapshot_rule_flags_residency_pairing():
    diags = _diags("fixture_residency_missing.py", ["BTX-SNAPSHOT"])
    msgs = "\n".join(d.message for d in diags)
    # extract_keys with no inject_keys: stranded evictions.
    assert "HalfResidentState" in msgs
    assert "inject_keys" in msgs
    # The collective tier must implement NEITHER half.
    assert "EvictingGlobalState" in msgs
    assert any(
        "global_exchange" in d.message and "residency" in d.message
        for d in diags
    )


def test_snapshot_rule_flags_infer_broadcast_state():
    # The inference subsystem's broadcast-params state is ordinary
    # device-tier state to the analyzer: reachable from a make_state
    # factory, it must drain its params row via demotion_snapshots.
    diags = _diags("fixture_infer_snapshot.py", ["BTX-SNAPSHOT"])
    assert [d.rule for d in diags] == ["BTX-SNAPSHOT"]
    assert "BroadcastParamsState" in diags[0].message
    assert "EagerInferSpec.make_state" in diags[0].message
    assert "demotion_snapshots" in diags[0].message


def test_gsync_rule_flags_per_batch_swap_agreement():
    # A params-swap vote belongs in the epoch-close "fstat" round; an
    # infer runtime entering a sync round from its per-batch `update`
    # (behind a bound-method alias) is the same deadlock shape as any
    # smuggled collective.
    diags = _diags("fixture_infer_gsync.py", ["BTX-GSYNC"])
    reach = [d for d in diags if "per-batch path" in d.message]
    assert reach, diags
    assert "EagerSwapInfer.update" in reach[0].message
    assert "_agree_swap" in reach[0].message  # witness chain
    source = (FIXTURES / "fixture_infer_gsync.py").read_text()
    body = "\n".join(
        line
        for line in source.splitlines()
        if not line.lstrip().startswith(("#", '"', "'"))
    )
    assert not re.search(r"global_sync\s*\(", body)


def test_thread_rule_flags_worker_lane_alias_send():
    diags = _diags("fixture_thread_worker_send.py", ["BTX-THREAD"])
    assert [d.rule for d in diags] == ["BTX-THREAD"]
    msg = diags[0].message
    # The callable was traced INTO the thread submission (a nested
    # def is the worker-lane root)...
    assert "LeakyStep.process.<locals>.task" in msg
    # ...and the send surface was reached through a bound-method
    # alias — no line in the fixture spells `comm.send(...)`.
    assert "alias of a raw cluster send" in msg
    source = (FIXTURES / "fixture_thread_worker_send.py").read_text()
    assert not re.search(r"comm\s*\.\s*send\s*\(", source)
    # The diagnostic lands at the submit site, where a deliberate
    # exception would be waived.
    assert "self._pipe.push(task, finalize)" in source.splitlines()[
        diags[0].lineno - 1
    ]


def test_drain_rule_flags_per_batch_eviction_and_flush():
    diags = _diags("fixture_drain_per_batch.py", ["BTX-DRAIN"])
    msgs = "\n".join(d.message for d in diags)
    # Eviction reachable from a per-batch path, with a witness chain.
    assert "evict_to_budget" in msgs
    assert "EagerStep.process -> EagerStep._maybe_trim" in msgs
    # Raw pipeline drain on a per-batch path (receiver-typed seed).
    assert "DevicePipeline.flush" in msgs
    # Flush-before-sync: the gsync primitive hides behind a
    # bound-method alias and still gets flagged.
    assert "without first flushing" in msgs
    assert {d.rule for d in diags} == {"BTX-DRAIN"}


def test_knob_rule_flags_uncataloged_and_computed_reads():
    diags = _diags("fixture_knob_uncataloged.py", ["BTX-KNOB"])
    msgs = "\n".join(d.message for d in diags)
    assert "uncataloged knob BYTEWAX_TPU_TURBO" in msgs
    assert "computed BYTEWAX_TPU_* knob name" in msgs
    # Subscript loads are reads too.
    assert "BYTEWAX_TPU_SECRET_MODE" in msgs
    # A knob name bound to a variable first cannot slip the catalog.
    assert "BYTEWAX_TPU_STEALTH_MODE" in msgs
    assert len(diags) == 4


def test_lane_rule_flags_uncataloged_construction():
    diags = _diags("fixture_lane_uncataloged.py", ["BTX-LANE"])
    # The module drains its lane and uses a cataloged phase — the ONE
    # finding is catalog closure.
    assert [d.rule for d in diags] == ["BTX-LANE"]
    assert "un-cataloged lane" in diags[0].message
    assert "SneakyStep.__init__" in diags[0].message
    # The diagnostic lands on the construction line.
    source = (FIXTURES / "fixture_lane_uncataloged.py").read_text()
    assert "DevicePipeline(" in source.splitlines()[diags[0].lineno - 1]


def test_lane_rule_flags_unfenced_module():
    diags = _diags("fixture_lane_unfenced.py", ["BTX-LANE"])
    msgs = "\n".join(d.message for d in diags)
    # The module flushes but never tears down: the un-fenced finding
    # names exactly the missing half.
    unfenced = [d for d in diags if "un-fenced lane" in d.message]
    assert unfenced, diags
    assert ".shutdown()/.drop_pending()" in unfenced[0].message
    assert ".flush()" not in unfenced[0].message
    # (The un-cataloged finding fires too — the fixture lane is not
    # in contracts.LANES either.)
    assert "un-cataloged lane" in msgs


def test_lane_rule_flags_unknown_ledger_phase():
    diags = _diags("fixture_lane_phase.py", ["BTX-LANE"])
    phase = [d for d in diags if "unknown ledger phase" in d.message]
    assert phase, diags
    assert "'turbo_lane'" in phase[0].message
    # The message routes the reader to the observable consequence.
    assert "ledger bucket" in phase[0].message


def test_race_rule_flags_alias_smuggled_write():
    diags = _diags("fixture_race_alias.py", ["BTX-RACE"])
    assert [d.rule for d in diags] == ["BTX-RACE"]
    msg = diags[0].message
    assert "RacyStep._tally" in msg
    # DUAL witness chains: the worker path resolves the bound-method
    # alias into the write...
    assert "RacyStep.process.<locals>.task -> RacyStep._bump" in msg
    # ...and the main path shows the per-batch access.
    assert "(via RacyStep.process" in msg
    # No line inside the task spells a self-attribute store — only
    # alias resolution can see the worker-side write.
    source = (FIXTURES / "fixture_race_alias.py").read_text()
    task = source[source.index("def task") : source.index("def finalize")]
    assert "self._tally" not in task
    # The diagnostic lands at the worker-side write site.
    assert "def _bump" in source.splitlines()[diags[0].lineno - 1]


def test_new_rule_waiver_round_trip(tmp_path):
    """Each new rule's finding is suppressed by an inline waiver on
    the flagged line — the same escape hatch the engine's deliberate
    exceptions use — and reappears when the waiver is removed."""
    cases = {
        "fixture_thread_worker_send.py": "BTX-THREAD",
        "fixture_drain_per_batch.py": "BTX-DRAIN",
        "fixture_knob_uncataloged.py": "BTX-KNOB",
        "fixture_lane_uncataloged.py": "BTX-LANE",
        "fixture_race_alias.py": "BTX-RACE",
        "fixture_infer_snapshot.py": "BTX-SNAPSHOT",
        "fixture_infer_gsync.py": "BTX-GSYNC",
    }
    for name, rule in cases.items():
        diags = _diags(name, [rule])
        assert diags, name
        lines = (FIXTURES / name).read_text().splitlines()
        for d in diags:
            lines[d.lineno - 1] += f"  # bytewax: allow[{rule}]"
        waived = tmp_path / name
        waived.write_text("\n".join(lines) + "\n")
        after, _s, _p = analyze_paths(
            [waived], rule_ids=[rule], rel_root=tmp_path
        )
        assert not after, (name, after)


def test_backend_rule_flags_unforced_script():
    diags = _diags(
        "fixture_backend_script.py", ["BTX-BACKEND"], scripts=True
    )
    assert [d.rule for d in diags] == ["BTX-BACKEND"]
    assert "run entry point" in diags[0].message
    # The same file scanned as a library module is exempt: only
    # standalone execution reaches jax init unforced.
    assert not _diags("fixture_backend_script.py", ["BTX-BACKEND"])


# -- waivers ----------------------------------------------------------------


def test_inline_waiver_suppresses_finding():
    diags = _diags("fixture_waived.py")
    assert not diags


def test_waiver_parsing_is_comment_token_based():
    # A '#' inside a string literal neither creates a waiver nor
    # truncates the line (the old _strip_comments bug hid real calls
    # this way).
    w = Waivers.parse(
        'x = "# bytewax: allow[BTX-SEND]"\n'
        "y = 1  # bytewax: allow[BTX-FRAMES]\n"
    )
    assert not w.waives(1, "BTX-SEND")
    assert w.waives(2, "BTX-FRAMES")
    # Multi-id waivers and the line-above form.
    w2 = Waivers.parse("# bytewax: allow[BTX-A,BTX-B]\ncall()\n")
    assert w2.waives(2, "BTX-A") and w2.waives(2, "BTX-B")
    assert not w2.waives(2, "BTX-C")


def test_string_literal_hash_does_not_hide_calls():
    # fixture_waived.tagged_flush sends a frame whose kind comes from
    # a string containing '#'; with waivers stripped the analyzer
    # must still SEE the call (the old line-split comment stripping
    # dropped everything after the '#', hiding it).
    source = (FIXTURES / "fixture_waived.py").read_text()
    unwaived = source.replace("# bytewax: allow", "# waiver removed ")
    probe = FIXTURES / "_probe_unwaived.py"
    probe.write_text(unwaived)
    try:
        diags, _s, _p = analyze_paths(
            [probe], rule_ids=["BTX-SEND"], rel_root=REPO
        )
        assert len(diags) == 2  # both sends, incl. the '#'-string one
    finally:
        probe.unlink()


# -- baseline ---------------------------------------------------------------


def test_baseline_round_trip(tmp_path):
    diags = _diags("fixture_send_alias.py", ["BTX-SEND"])
    assert diags
    baseline = tmp_path / "BASELINE"
    write_baseline(baseline, diags)
    loaded = load_baseline(baseline)
    remaining, suppressed = apply_baseline(diags, loaded)
    assert not remaining
    assert suppressed == len(diags)
    # And through the public API path.
    diags2, suppressed2, _p = analyze_paths(
        [FIXTURES / "fixture_send_alias.py"],
        rule_ids=["BTX-SEND"],
        baseline=baseline,
        rel_root=REPO,
    )
    assert not diags2
    assert suppressed2 == len(diags)


def test_baseline_is_line_number_free(tmp_path):
    d1 = Diagnostic("BTX-X", "a.py", 10, "msg")
    d2 = Diagnostic("BTX-X", "a.py", 99, "msg")
    baseline = tmp_path / "BASELINE"
    write_baseline(baseline, [d1])
    remaining, suppressed = apply_baseline(
        [d2], load_baseline(baseline)
    )
    assert not remaining and suppressed == 1


def test_missing_baseline_is_empty(tmp_path):
    assert load_baseline(tmp_path / "nope") == set()
    assert load_baseline(None) == set()


def test_unknown_rule_id_raises():
    with pytest.raises(KeyError):
        _diags("fixture_send_alias.py", ["BTX-NOPE"])
