"""Multi-process cluster execution tests (model:
``/root/reference/pytests/test_execution.py`` — real subprocesses
forming a localhost TCP mesh)."""

import os
import signal
import subprocess
import sys
import time
from pathlib import Path

import pytest

_FLOW_TEMPLATE = '''
import os
import bytewax_tpu.operators as op
from bytewax_tpu.dataflow import Dataflow
from bytewax_tpu.connectors.files import FileSink
from bytewax_tpu.inputs import DynamicSource, StatelessSourcePartition


class _Part(StatelessSourcePartition):
    def __init__(self, worker_index):
        self._items = [
            (f"key-{{i}}", 1) for i in range(worker_index * 8, worker_index * 8 + 8)
        ] * 3
        self._done = False

    def next_batch(self):
        if self._done:
            raise StopIteration()
        self._done = True
        return self._items


class PerWorkerSource(DynamicSource):
    def build(self, step_id, worker_index, worker_count):
        return _Part(worker_index)


flow = Dataflow("cluster_df")
s = op.input("inp", flow, PerWorkerSource())
summed = op.reduce_final("sum", s, lambda a, b: a + b)
fmt = op.map_value("fmt", summed, str)
op.output("out", fmt, FileSink({out_path!r}))
'''


def _write_flow(tmp_path: Path) -> Path:
    out_path = str(tmp_path / "out.txt")
    flow_py = tmp_path / "cluster_flow.py"
    flow_py.write_text(_FLOW_TEMPLATE.format(out_path=out_path))
    return flow_py


def _env():
    env = dict(os.environ)
    env["PYTHONPATH"] = (
        "/root/repo" + os.pathsep + env.get("PYTHONPATH", "")
    )
    env["BYTEWAX_TPU_PLATFORM"] = "cpu"
    env["BYTEWAX_TPU_ACCEL"] = "0"  # keep subprocess startup light
    return env


@pytest.mark.parametrize("procs,wpp", [(2, 1), (2, 2)])
def test_cluster_keyed_exchange(tmp_path, procs, wpp):
    flow_py = _write_flow(tmp_path)
    res = subprocess.run(
        [
            sys.executable,
            "-m",
            "bytewax_tpu.testing",
            f"{flow_py}:flow",
            "-p",
            str(procs),
            "-w",
            str(wpp),
        ],
        env=_env(),
        cwd=tmp_path,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert res.returncode == 0, res.stderr[-2000:]
    out = (tmp_path / "out.txt").read_text().splitlines()
    # Each worker lane emits 8 unique keys 3 times; every key must be
    # summed exactly once (to "3"), wherever its home lane lives.
    assert sorted(out) == ["3"] * 8 * procs * wpp


def test_cluster_sigint_clean_shutdown(tmp_path):
    # An infinite source; SIGINT must terminate all processes.
    flow_py = tmp_path / "infinite_flow.py"
    flow_py.write_text(
        """
import time
import bytewax_tpu.operators as op
from bytewax_tpu.dataflow import Dataflow
from bytewax_tpu.connectors.stdio import StdOutSink
from bytewax_tpu.inputs import DynamicSource, StatelessSourcePartition


class _Tick(StatelessSourcePartition):
    def next_batch(self):
        time.sleep(0.01)
        return ["tick"]


class TickSource(DynamicSource):
    def build(self, step_id, worker_index, worker_count):
        return _Tick()


flow = Dataflow("inf_df")
s = op.input("inp", flow, TickSource())
s = op.filter("drop", s, lambda _x: False)
op.output("out", s, StdOutSink())
"""
    )
    proc = subprocess.Popen(
        [
            sys.executable,
            "-m",
            "bytewax_tpu.testing",
            f"{flow_py}:flow",
            "-p",
            "2",
            "-w",
            "1",
        ],
        env=_env(),
        cwd=tmp_path,
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
        start_new_session=True,
    )
    time.sleep(8)  # let the cluster form and run
    assert proc.poll() is None, "cluster exited prematurely"
    os.killpg(proc.pid, signal.SIGINT)
    try:
        proc.wait(timeout=30)
    except subprocess.TimeoutExpired:
        os.killpg(proc.pid, signal.SIGKILL)
        raise AssertionError("cluster did not shut down on SIGINT")


def test_cluster_recovery_continuation(tmp_path):
    # Two executions of a 2-proc cluster with a shared recovery store:
    # the second resumes after the EOF sentinel.
    flow_py = tmp_path / "rec_flow.py"
    out_path = str(tmp_path / "out.txt")
    flow_py.write_text(
        f'''
import bytewax_tpu.operators as op
from bytewax_tpu.dataflow import Dataflow
from bytewax_tpu.connectors.files import FileSink
from bytewax_tpu.testing import TestingSource

inp = ["a", "b", TestingSource.EOF(), "c", "d"]
flow = Dataflow("rec_df")
s = op.input("inp", flow, TestingSource(inp))
s = op.key_on("key", s, lambda x: x)
op.output("out", s, FileSink({out_path!r}))
'''
    )
    db = tmp_path / "db"
    db.mkdir()
    subprocess.run(
        [sys.executable, "-m", "bytewax_tpu.recovery", str(db), "2"],
        env=_env(),
        check=True,
        timeout=60,
    )

    def run_cluster():
        return subprocess.run(
            [
                sys.executable,
                "-m",
                "bytewax_tpu.testing",
                f"{flow_py}:flow",
                "-p",
                "2",
                "-r",
                str(db),
                "-s",
                "0",
                "-b",
                "0",
            ],
            env=_env(),
            cwd=tmp_path,
            capture_output=True,
            text=True,
            timeout=120,
        )

    res = run_cluster()
    assert res.returncode == 0, res.stderr[-2000:]
    assert sorted(Path(out_path).read_text().split()) == ["a", "b"]

    res = run_cluster()
    assert res.returncode == 0, res.stderr[-2000:]
    assert sorted(Path(out_path).read_text().split()) == ["a", "b", "c", "d"]


@pytest.mark.parametrize("accel", ["0", "1"])
def test_cluster_columnar_windowed_sum(tmp_path, accel):
    # A {'key','ts','value'} columnar source in a 2-proc cluster: the
    # keyed exchange degrades batches to (key, TsValue) items and
    # ships them to their home lane; window sums must cover every row
    # on both tiers.
    flow_py = tmp_path / "colwin_flow.py"
    out_path = str(tmp_path / "out.txt")
    flow_py.write_text(
        f'''
from datetime import datetime, timedelta, timezone

import numpy as np

import bytewax_tpu.operators as op
import bytewax_tpu.operators.windowing as w
from bytewax_tpu import xla
from bytewax_tpu.connectors.files import FileSink
from bytewax_tpu.dataflow import Dataflow
from bytewax_tpu.engine.arrays import ArrayBatch
from bytewax_tpu.inputs import DynamicSource, StatelessSourcePartition
from bytewax_tpu.operators.windowing import EventClock, TumblingWindower

ALIGN = datetime(2022, 1, 1, tzinfo=timezone.utc)


class _Part(StatelessSourcePartition):
    def __init__(self, worker_index):
        self._batches = []
        if worker_index == 0:
            n = 400
            rng = np.random.RandomState(0)
            secs = np.sort(rng.randint(0, 180, size=n))
            keys = np.array([f"key{{k}}" for k in rng.randint(0, 8, size=n)])
            vals = np.ones(n)
            ts = (
                np.datetime64("2022-01-01", "us")
                + secs.astype("timedelta64[s]")
            )
            self._batches = [
                ArrayBatch(
                    {{
                        "key": keys[i : i + 128],
                        "ts": ts[i : i + 128],
                        "value": vals[i : i + 128],
                    }}
                )
                for i in range(0, n, 128)
            ]

    def next_batch(self):
        if not self._batches:
            raise StopIteration()
        return self._batches.pop(0)


class BatchSource(DynamicSource):
    def build(self, step_id, worker_index, worker_count):
        return _Part(worker_index)


clock = EventClock(
    ts_getter=xla.column_ts,
    wait_for_system_duration=timedelta(seconds=5),
)
windower = TumblingWindower(length=timedelta(minutes=1), align_to=ALIGN)
flow = Dataflow("colwin_df")
s = op.input("inp", flow, BatchSource())
wo = w.reduce_window("sum", s, clock, windower, xla.SUM)
fmt = op.map(
    "fmt", wo.down, lambda kv: (kv[0], f"{{kv[0]}} {{kv[1][0]}} {{kv[1][1]}}")
)
op.output("out", fmt, FileSink({out_path!r}))
'''
    )
    env = _env()
    env["BYTEWAX_TPU_ACCEL"] = accel
    res = subprocess.run(
        [
            sys.executable,
            "-m",
            "bytewax_tpu.testing",
            f"{flow_py}:flow",
            "-p",
            "2",
        ],
        env=env,
        cwd=tmp_path,
        capture_output=True,
        text=True,
        timeout=180,
    )
    assert res.returncode == 0, res.stderr[-3000:]
    total = 0.0
    seen = set()
    for line in Path(out_path).read_text().splitlines():
        key, wid, val = line.split()
        assert (key, wid) not in seen, "duplicate (key, window) emission"
        seen.add((key, wid))
        total += float(val)
    assert total == 400.0


def _free_port():
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_comm_rx_buffer_bounded(monkeypatch):
    # Two peers bulk-sending >100 MB to each other in one epoch with
    # an 4 MiB rx cap: no deadlock, nothing lost, and neither side's
    # raw rx buffer materially exceeds the cap.
    import threading

    from bytewax_tpu.engine.comm import Comm

    cap = 4 * 1024 * 1024
    monkeypatch.setenv("BYTEWAX_TPU_RX_BUFFER_CAP", str(cap))
    addrs = [f"127.0.0.1:{_free_port()}", f"127.0.0.1:{_free_port()}"]
    n_msgs, msg_len = 60, 1_000_000  # ~60 MB each direction
    payload = b"x" * msg_len
    results = {}
    errors = []
    finished = threading.Barrier(2, timeout=120)

    def run(pid):
        try:
            comm = Comm(addrs, pid)
            got = []
            # Ship everything, then drain until the peer's full set
            # arrives (send() itself drains while blocked).
            for i in range(n_msgs):
                comm.send(1 - pid, (i, payload))
            comm.send(1 - pid, "done")
            done = False
            while not done or len(got) < n_msgs:
                for _peer, msg in comm.recv_ready(0.01):
                    if msg == "done":
                        done = True
                    else:
                        got.append(msg)
            results[pid] = (got, comm.rx_peak)
            finished.wait()  # both sides drained before either closes
            comm.close()
        except BaseException as ex:  # noqa: BLE001
            errors.append((pid, ex))

    threads = [threading.Thread(target=run, args=(p,)) for p in (0, 1)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
        assert not t.is_alive(), "comm exchange deadlocked"
    assert not errors, errors
    for pid in (0, 1):
        got, peak = results[pid]
        assert sorted(i for i, _p in got) == list(range(n_msgs))
        assert all(p == payload for _i, p in got)
        # Raw buffer bounded: cap plus one read chunk of slack.
        assert peak <= cap + (1 << 20), f"peer {pid} rx peaked at {peak}"


def test_comm_single_frame_larger_than_cap(monkeypatch):
    # A single frame bigger than the cap must still be receivable
    # (effective bound = max(cap, largest frame)), not stall forever.
    import threading

    from bytewax_tpu.engine.comm import Comm

    monkeypatch.setenv("BYTEWAX_TPU_RX_BUFFER_CAP", str(1 << 20))
    addrs = [f"127.0.0.1:{_free_port()}", f"127.0.0.1:{_free_port()}"]
    big = b"y" * (5 << 20)
    results = {}
    errors = []

    def run(pid):
        try:
            comm = Comm(addrs, pid)
            if pid == 0:
                comm.send(1, ("big", big))
                got = []
                while not got:
                    got = comm.recv_ready(0.01)
                results[0] = got
            else:
                got = []
                while not got:
                    got = comm.recv_ready(0.01)
                results[1] = got
                comm.send(0, "ack")
            comm.close()
        except BaseException as ex:  # noqa: BLE001
            errors.append((pid, ex))

    threads = [threading.Thread(target=run, args=(p,)) for p in (0, 1)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
        assert not t.is_alive(), "oversized-frame exchange stalled"
    assert not errors, errors
    assert results[1] == [(0, ("big", big))]
    assert results[0] == [(1, "ack")]


def test_cluster_peer_kill9_tears_down_and_resumes(tmp_path):
    # Chaos: kill -9 one worker process mid-stream; the surviving
    # process must detect the dead peer and exit instead of hanging,
    # and a restarted cluster must resume from the last snapshot.
    flow_py = tmp_path / "chaos_flow.py"
    out_path = str(tmp_path / "out.txt")
    flow_py.write_text(
        f'''
import itertools
import os
import time

import bytewax_tpu.operators as op
from bytewax_tpu.dataflow import Dataflow
from bytewax_tpu.connectors.files import FileSink
from bytewax_tpu.inputs import FixedPartitionedSource, StatefulSourcePartition


class _Part(StatefulSourcePartition):
    """Emits key-i sequentially, forever unless capped."""

    def __init__(self, resume):
        self._i = resume or 0

    def next_batch(self):
        cap = int(os.environ.get("CHAOS_CAP", "0"))
        if cap and self._i >= cap:
            raise StopIteration()
        self._i += 1
        time.sleep(0.01)
        return [(f"key-{{self._i % 4}}", self._i)]

    def snapshot(self):
        return self._i


class SeqSource(FixedPartitionedSource):
    def list_parts(self):
        return ["p0", "p1"]

    def build_part(self, step_id, name, resume):
        return _Part(resume)


flow = Dataflow("chaos_df")
s = op.input("inp", flow, SeqSource())
s = op.map_value("fmt", s, str)
op.output("out", s, FileSink({out_path!r}))
'''
    )
    db = tmp_path / "db"
    db.mkdir()
    subprocess.run(
        [sys.executable, "-m", "bytewax_tpu.recovery", str(db), "2"],
        env=_env(),
        check=True,
        timeout=60,
    )
    args = [
        sys.executable,
        "-m",
        "bytewax_tpu.testing",
        f"{flow_py}:flow",
        "-p",
        "2",
        "-r",
        str(db),
        "-s",
        "0",
        "-b",
        "0",
    ]
    proc = subprocess.Popen(
        args,
        env=_env(),
        cwd=tmp_path,
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
        start_new_session=True,
    )
    # Wait for REAL progress, not wall clock: the replay-bound
    # assertion below needs every partition's snapshot past the
    # restart cap (40), so let the cluster write well beyond 2 x 44
    # rows before killing — a fixed sleep flakes when startup is slow
    # under suite load.
    deadline = time.monotonic() + 90
    while time.monotonic() < deadline:
        assert proc.poll() is None, "cluster exited prematurely"
        try:
            if len(Path(out_path).read_text().split()) >= 120:
                break
        except OSError:
            pass
        time.sleep(0.5)
    else:
        os.killpg(proc.pid, signal.SIGKILL)
        raise AssertionError("cluster made no progress before the kill")
    # SIGKILL one WORKER (a child of the spawner), not the spawner.
    children = subprocess.run(
        ["pgrep", "-P", str(proc.pid)], capture_output=True, text=True
    ).stdout.split()
    assert children, "no worker children found"
    os.kill(int(children[0]), signal.SIGKILL)
    try:
        rc = proc.wait(timeout=60)
    except subprocess.TimeoutExpired:
        os.killpg(proc.pid, signal.SIGKILL)
        raise AssertionError(
            "cluster hung after a worker was SIGKILLed mid-epoch"
        )
    assert rc != 0  # a crash is not a clean exit

    before = Path(out_path).read_text().split()
    assert before, "nothing was written before the kill"

    # Restart with a cap: the resume math must accept the crashed
    # execution's partial progress and run to a clean EOF.
    env = _env()
    env["CHAOS_CAP"] = "40"
    res = subprocess.run(
        args,
        env=env,
        cwd=tmp_path,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert res.returncode == 0, res.stderr[-2000:]
    after = Path(out_path).read_text().split()
    # Sources resumed from their snapshots (already past the cap), so
    # at most the last uncommitted micro-batch per partition replays —
    # a from-scratch run would instead append 2 x 40 fresh rows.
    assert len(after) - len(before) <= 4, (len(before), len(after))


def test_cluster_3proc_recovery_rescale(tmp_path):
    # 3-proc cluster writes snapshots; a 2-proc cluster resumes the
    # same store (elastic rescale across executions).  Rescale is an
    # explicit opt-in since the rescale PR — the resumed run passes
    # --rescale so the startup pass re-routes the keyed rows to the
    # 2-worker modulus (tests/test_rescale.py covers the refusal and
    # crash-retry paths).
    flow_py = tmp_path / "rescale_flow.py"
    out_path = str(tmp_path / "out.txt")
    flow_py.write_text(
        f'''
import bytewax_tpu.operators as op
from bytewax_tpu.dataflow import Dataflow
from bytewax_tpu.connectors.files import FileSink
from bytewax_tpu.testing import TestingSource

inp = [(f"k{{i % 5}}", 1) for i in range(20)] + [TestingSource.EOF()] + [
    (f"k{{i % 5}}", 1) for i in range(20, 30)
]
flow = Dataflow("rescale_df")
s = op.input("inp", flow, TestingSource(inp))
summed = op.stateful_map(
    "sum", s, lambda st, v: ((st or 0) + v, (st or 0) + v)
)
fmt = op.map_value("fmt", summed, str)
op.output("out", fmt, FileSink({out_path!r}))
'''
    )
    db = tmp_path / "db"
    db.mkdir()
    subprocess.run(
        [sys.executable, "-m", "bytewax_tpu.recovery", str(db), "3"],
        env=_env(),
        check=True,
        timeout=60,
    )

    def run_cluster(procs, rescale=False):
        return subprocess.run(
            [
                sys.executable,
                "-m",
                "bytewax_tpu.testing",
                f"{flow_py}:flow",
                "-p",
                str(procs),
                "-r",
                str(db),
                "-s",
                "0",
                "-b",
                "0",
            ]
            + (["--rescale"] if rescale else []),
            env=_env(),
            cwd=tmp_path,
            capture_output=True,
            text=True,
            timeout=180,
        )

    res = run_cluster(3)
    assert res.returncode == 0, res.stderr[-2000:]
    first = Path(out_path).read_text().split()
    assert len(first) == 20

    res = run_cluster(2, rescale=True)
    assert res.returncode == 0, res.stderr[-2000:]
    lines = Path(out_path).read_text().split()
    # The running sums continue from the snapshotted state: the final
    # counts per key must cover all 30 items exactly once.
    assert len(lines) == 30
    assert max(int(x) for x in lines) == 6  # 30 items / 5 keys


def test_cluster_jax_distributed_init(tmp_path):
    # BYTEWAX_TPU_DISTRIBUTED=1: each cluster process joins one jax
    # distributed runtime (global devices = sum of locals) while the
    # dataflow's keyed exchange still routes over the host mesh —
    # the multi-host pod composition, exercised on CPU.
    flow_py = tmp_path / "dist_flow.py"
    out_path = str(tmp_path / "out.txt")
    flow_py.write_text(
        f'''
import bytewax_tpu.operators as op
from bytewax_tpu.dataflow import Dataflow
from bytewax_tpu.connectors.files import FileSink
from bytewax_tpu.inputs import DynamicSource, StatelessSourcePartition


class _Part(StatelessSourcePartition):
    def __init__(self, worker_index):
        self._items = [(f"key-{{i}}", 1) for i in range(8)]
        self._done = worker_index != 0

    def next_batch(self):
        if self._done:
            raise StopIteration()
        self._done = True
        import jax

        # Inside a worker: the distributed runtime is live — the
        # global device view is both processes' locals combined.
        assert jax.process_count() == 2, jax.process_count()
        assert (
            jax.device_count() == 2 * jax.local_device_count()
        ), (jax.device_count(), jax.local_device_count())
        return self._items


class Src(DynamicSource):
    def build(self, step_id, worker_index, worker_count):
        return _Part(worker_index)


flow = Dataflow("dist_df")
s = op.input("inp", flow, Src())
summed = op.reduce_final("sum", s, lambda a, b: a + b)
fmt = op.map_value("fmt", summed, str)
op.output("out", fmt, FileSink({out_path!r}))
'''
    )
    env = _env()
    env["BYTEWAX_TPU_DISTRIBUTED"] = "1"
    res = subprocess.run(
        [
            sys.executable,
            "-m",
            "bytewax_tpu.testing",
            f"{flow_py}:flow",
            "-p",
            "2",
        ],
        env=env,
        cwd=tmp_path,
        capture_output=True,
        text=True,
        timeout=180,
    )
    assert res.returncode == 0, res.stderr[-3000:]
    assert sorted(Path(out_path).read_text().split()) == ["1"] * 8


def test_comm_heartbeat_detects_frozen_peer(monkeypatch):
    # A frozen peer (socket open, nothing sent — no TCP close ever
    # arrives) must be declared dead within the heartbeat bound
    # (~2.5 intervals), with a clear coordinator-naming error.
    import threading
    import time as _time

    from bytewax_tpu.engine.comm import Comm

    hb = 0.2
    monkeypatch.setenv("BYTEWAX_TPU_HEARTBEAT_S", str(hb))
    addrs = [f"127.0.0.1:{_free_port()}", f"127.0.0.1:{_free_port()}"]
    errors = {}
    frozen = threading.Event()

    def run_live():
        comm = Comm(addrs, 1)
        t0 = _time.monotonic()
        try:
            while True:
                comm.recv_ready(0.02)
                if _time.monotonic() - t0 > 20:
                    errors[1] = ("timeout", None)
                    return
        except ConnectionError as ex:
            errors[1] = (str(ex), _time.monotonic() - t0)
        finally:
            comm.close()

    def run_frozen():
        comm = Comm(addrs, 0)
        # Handshake done; now freeze (no pumping, no close).
        frozen.wait(timeout=20)
        comm.close()

    threads = [
        threading.Thread(target=run_frozen),
        threading.Thread(target=run_live),
    ]
    for t in threads:
        t.start()
    threads[1].join(timeout=25)
    frozen.set()
    threads[0].join(timeout=5)
    msg, elapsed = errors[1]
    assert "coordinator (process 0)" in msg, msg
    assert "heartbeat" in msg
    # Detection within the documented bound (plus scheduling slack).
    assert elapsed is not None and elapsed < hb * 2.5 + 1.0, elapsed
    assert elapsed > hb * 2.0  # not trigger-happy either


def test_comm_heartbeats_keep_idle_cluster_alive(monkeypatch):
    # Two idle-but-pumping peers exchange heartbeats and survive far
    # past the detection limit; heartbeat frames are never delivered.
    import threading
    import time as _time

    from bytewax_tpu.engine.comm import Comm

    hb = 0.1
    monkeypatch.setenv("BYTEWAX_TPU_HEARTBEAT_S", str(hb))
    addrs = [f"127.0.0.1:{_free_port()}", f"127.0.0.1:{_free_port()}"]
    got = {0: [], 1: []}
    errors = []
    done = threading.Barrier(2, timeout=25)

    def run(pid):
        try:
            comm = Comm(addrs, pid)
            deadline = _time.monotonic() + hb * 12
            while _time.monotonic() < deadline:
                got[pid].extend(comm.recv_ready(0.02))
            comm.send(1 - pid, ("real", pid))
            want = (1 - pid, ("real", 1 - pid))
            while want not in got[pid]:
                got[pid].extend(comm.recv_ready(0.02))
            done.wait()  # both drained before either closes
            comm.close()
        except BaseException as ex:  # noqa: BLE001
            errors.append((pid, ex))

    threads = [threading.Thread(target=run, args=(p,)) for p in (0, 1)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert not errors, errors
    # Only the real messages arrived; heartbeats were swallowed.
    assert got[0] == [(1, ("real", 1))]
    assert got[1] == [(0, ("real", 0))]


def test_comm_heartbeat_no_false_positive_on_partial_traffic(monkeypatch):
    # 3 peers; peer 1 sends real data only to peer 0.  Peer 2 must
    # keep seeing peer 1's heartbeats (per-peer tx tracking) and
    # never declare it dead.
    import threading
    import time as _time

    from bytewax_tpu.engine.comm import Comm

    hb = 0.15
    monkeypatch.setenv("BYTEWAX_TPU_HEARTBEAT_S", str(hb))
    addrs = [f"127.0.0.1:{_free_port()}" for _ in range(3)]
    errors = []
    done = threading.Barrier(3, timeout=30)

    def run(pid):
        try:
            comm = Comm(addrs, pid)
            deadline = _time.monotonic() + hb * 15
            while _time.monotonic() < deadline:
                if pid == 1:
                    comm.send(0, ("chatter", pid))
                comm.recv_ready(0.02)
                _time.sleep(0.02)
            done.wait()
            comm.close()
        except BaseException as ex:  # noqa: BLE001
            errors.append((pid, ex))

    threads = [threading.Thread(target=run, args=(p,)) for p in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=40)
    assert not errors, errors


def test_cluster_global_mesh_exchange(tmp_path):
    """BYTEWAX_TPU_DISTRIBUTED=1 + accel, no recovery store: keyed
    aggregation rows ride ONE collective all_to_all over the global
    device mesh at epoch close (GlobalAggState) — the host TCP mesh
    carries only control-plane metadata.  Both workers produce rows
    for every key, so a correct answer REQUIRES the cross-process
    exchange; the debug marker proves the collective ran on both
    processes, and the output must match the same flow over the
    pickled-TCP tier."""
    flow_py = tmp_path / "gx_flow.py"
    out_path = str(tmp_path / "out.txt")
    flow_py.write_text(
        f'''
import bytewax_tpu.operators as op
from bytewax_tpu import xla
from bytewax_tpu.dataflow import Dataflow
from bytewax_tpu.connectors.files import FileSink
from bytewax_tpu.inputs import DynamicSource, StatelessSourcePartition


class _Part(StatelessSourcePartition):
    def __init__(self, worker_index):
        base = worker_index * 1000
        self._batches = [
            [(f"k{{i % 7}}", float(base + i)) for i in range(200)],
            [(f"k{{i % 7}}", float(base + 200 + i)) for i in range(200)],
        ]

    def next_batch(self):
        if not self._batches:
            raise StopIteration()
        return self._batches.pop(0)


class Src(DynamicSource):
    def build(self, step_id, worker_index, worker_count):
        return _Part(worker_index)


flow = Dataflow("gx_df")
s = op.input("inp", flow, Src())
st = xla.stats_final("stats", s)
fmt = op.map(
    "fmt",
    st,
    lambda kv: (
        kv[0],
        f"{{kv[0]}};{{kv[1][0]}};{{kv[1][1]:.6f}};{{kv[1][2]}};{{kv[1][3]}}",
    ),
)
vals = op.map_value("val", fmt, lambda v: v)
op.output("out", vals, FileSink({out_path!r}))
'''
    )

    def run(global_exchange):
        env = _env()
        env["BYTEWAX_TPU_ACCEL"] = "1"
        env["BYTEWAX_TPU_DISTRIBUTED"] = "1"
        env["BYTEWAX_TPU_GLOBAL_EXCHANGE"] = (
            "1" if global_exchange else "0"
        )
        env["BYTEWAX_TPU_GLOBAL_EXCHANGE_DEBUG"] = "1"
        res = subprocess.run(
            [
                sys.executable,
                "-m",
                "bytewax_tpu.testing",
                f"{flow_py}:flow",
                "-p",
                "2",
            ],
            env=env,
            cwd=tmp_path,
            capture_output=True,
            text=True,
            timeout=180,
        )
        assert res.returncode == 0, res.stderr[-3000:]
        lines = sorted(Path(out_path).read_text().split())
        Path(out_path).unlink()
        return lines, res.stderr

    got, stderr = run(global_exchange=True)
    # Both processes entered the collective flush.
    assert stderr.count("global-exchange: proc 0 flushed") >= 1, stderr[-2000:]
    assert stderr.count("global-exchange: proc 1 flushed") >= 1, stderr[-2000:]

    # Oracle: stats per key over both workers' rows.
    rows = {}
    for base in (0, 1000):
        for i in range(200):
            rows.setdefault(f"k{i % 7}", []).append(float(base + i))
            rows.setdefault(f"k{i % 7}", []).append(float(base + 200 + i))
    want = sorted(
        f"{k};{min(g)};{sum(g) / len(g):.6f};{max(g)};{len(g)}"
        for k, g in rows.items()
    )
    assert got == want

    # And byte-identical with the TCP keyed-exchange tier.
    got_tcp, stderr_tcp = run(global_exchange=False)
    assert "global-exchange" not in stderr_tcp
    assert got_tcp == got


def test_cluster_wire_frame_accounting(monkeypatch):
    """Columnar exchange on a real 2-proc TCP mesh (both drivers in
    this process, one thread each): a columnar redistribute ships
    exactly ONE merged columnar frame per direction — per-slice
    frames coalesce in the route accumulator and zero-row slices
    never hit the wire — and the merged outputs cover every row
    exactly once (docs/performance.md "Columnar exchange")."""
    import threading

    import numpy as np

    import bytewax_tpu.operators as op
    from bytewax_tpu.dataflow import Dataflow
    from bytewax_tpu.engine import flight
    from bytewax_tpu.engine.arrays import ArrayBatch
    from bytewax_tpu.engine.driver import cluster_main
    from bytewax_tpu.inputs import DynamicSource, StatelessSourcePartition
    from bytewax_tpu.testing import TestingSink

    monkeypatch.setenv("BYTEWAX_TPU_ACCEL", "0")
    addrs = [f"127.0.0.1:{_free_port()}", f"127.0.0.1:{_free_port()}"]
    n = 64  # per worker

    class _Part(StatelessSourcePartition):
        def __init__(self, worker_index):
            lo = worker_index * n
            self._batches = [
                ArrayBatch(
                    {
                        "key": np.array(
                            [f"w{worker_index}k{i}" for i in range(n)]
                        ),
                        "value": np.arange(lo, lo + n, dtype=np.float64),
                    }
                )
            ]

        def next_batch(self):
            if not self._batches:
                raise StopIteration()
            return self._batches.pop(0)

    class Src(DynamicSource):
        def build(self, step_id, worker_index, worker_count):
            return _Part(worker_index)

    outs = [[], []]
    errors = []

    def flow_for(pid):
        flow = Dataflow("wire_frames_df")
        s = op.input("inp", flow, Src())
        s = op.redistribute("redist", s)
        op.output("out", s, TestingSink(outs[pid]))
        return flow

    def run(pid):
        try:
            cluster_main(flow_for(pid), addrs, pid)
        except BaseException as ex:  # noqa: BLE001
            errors.append((pid, ex))

    before = dict(flight.RECORDER.counters)
    threads = [threading.Thread(target=run, args=(p,)) for p in (0, 1)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
        assert not t.is_alive(), "wire exchange deadlocked"
    assert not errors, errors

    # Every row exactly once across both processes' sinks.
    got = sorted(
        kv for out in outs for kv in out
    )
    want = sorted(
        (f"w{wrk}k{i}", float(wrk * n + i))
        for wrk in (0, 1)
        for i in range(n)
    )
    assert got == want

    # The frame-count pin: each direction's 32 remote-lane rows ship
    # as ONE merged columnar frame (2 total in the whole cluster);
    # nothing else — no per-slice frames, no zero-row frames — put a
    # columnar frame on the wire.  (Both drivers share this
    # process's recorder, so the counters are cluster totals.)
    after = flight.RECORDER.counters

    def delta(name):
        return after.get(name, 0) - before.get(name, 0)

    assert delta("wire_encode_frames_columnar") == 2
    assert delta("wire_decode_frames_columnar") == 2
    # And the columnar payloads really dominated the shipped bytes of
    # the data plane: each frame carries a 32-row key/value batch.
    assert delta("wire_encode_bytes_columnar") > 2 * 32 * 8


_COLUMNAR_SEQ_FLOW = '''
import os
import time

import numpy as np

import bytewax_tpu.operators as op
from bytewax_tpu.dataflow import Dataflow
from bytewax_tpu.connectors.files import FileSink
from bytewax_tpu.engine.arrays import ArrayBatch
from bytewax_tpu.inputs import FixedPartitionedSource, StatefulSourcePartition

ROWS = 4  # rows per batch


class _Part(StatefulSourcePartition):
    """Columnar batches with exact resume: snapshot() is the batch
    index, so a supervised restart replays from the last committed
    epoch with byte-identical batches."""

    def __init__(self, name, resume):
        self._name = name
        self._i = resume or 0

    def next_batch(self):
        if self._i >= int(os.environ["CHAOS_CAP"]):
            raise StopIteration()
        self._i += 1
        i = self._i
        pace = float(os.environ.get("CHAOS_PACE_S", "0"))
        if pace:
            time.sleep(pace)
        return ArrayBatch(
            {{
                "key": np.array(
                    [f"{{self._name}}-{{(i + j) % 4}}" for j in range(ROWS)]
                ),
                "value": np.full(ROWS, i, dtype=np.int64),
            }}
        )

    def snapshot(self):
        return self._i


class SeqSource(FixedPartitionedSource):
    def list_parts(self):
        return ["p0", "p1"]

    def build_part(self, step_id, name, resume):
        return _Part(name, resume)


flow = Dataflow("wire_chaos_df")
s = op.input("inp", flow, SeqSource())
s = op.stateful_map("sum", s, lambda st, v: ((st or 0) + v, (st or 0) + v))
s = op.map("fmt", s, lambda kv: (kv[0], f"{{kv[0]}}={{kv[1]}}"))
op.output("out", s, FileSink({out_path!r}))
'''


def _columnar_seq_oracle(cap):
    rows = 4
    want = []
    for part in ("p0", "p1"):
        sums = {}
        for i in range(1, cap + 1):
            for j in range(rows):
                key = f"{part}-{(i + j) % 4}"
                sums[key] = sums.get(key, 0) + i
                want.append(f"{key}={sums[key]}")
    return sorted(want)


@pytest.mark.slow
@pytest.mark.parametrize("wire_mode", ["columnar", "pickle"])
def test_cluster_wire_crash_replay_exactly_once(tmp_path, wire_mode):
    """2-proc columnar keyed exchange with an injected worker crash
    mid-send (routed frames in flight at the crash): the supervisor
    restarts both processes, the restarted generation fences the dead
    generation's frames, and the final output equals the host oracle
    exactly-once.  Parametrized over both wire codecs so the crash
    semantics are proven identical (the pickle run is the PR's
    behavioral baseline)."""
    flow_py = tmp_path / f"wire_chaos_{wire_mode}.py"
    out_path = str(tmp_path / f"wire_chaos_{wire_mode}_out.txt")
    flow_py.write_text(_COLUMNAR_SEQ_FLOW.format(out_path=out_path))
    db = tmp_path / f"wire_chaos_{wire_mode}_db"
    db.mkdir()
    subprocess.run(
        [sys.executable, "-m", "bytewax_tpu.recovery", str(db), "2"],
        env=_env(),
        check=True,
        timeout=60,
    )
    cap = 30
    env = _env()
    env.update(
        {
            "CHAOS_CAP": str(cap),
            "BYTEWAX_TPU_WIRE": wire_mode,
            # Crash worker 1 inside a comm send at epoch 4 — after
            # routed slices of that epoch accumulated and (some)
            # shipped, before the epoch commits.
            "BYTEWAX_TPU_FAULTS": "comm.send:crash:4:1:x1",
            "BYTEWAX_TPU_MAX_RESTARTS": "3",
            "BYTEWAX_TPU_RESTART_BACKOFF_S": "0.1",
        }
    )
    res = subprocess.run(
        [
            sys.executable,
            "-m",
            "bytewax_tpu.testing",
            f"{flow_py}:flow",
            "-p",
            "2",
            "-r",
            str(db),
            "-s",
            "0",
            "-b",
            "0",
        ],
        env=env,
        cwd=tmp_path,
        capture_output=True,
        text=True,
        timeout=180,
    )
    assert res.returncode == 0, res.stderr[-3000:]
    assert "supervised restart" in res.stderr, res.stderr[-3000:]
    assert sorted(
        Path(out_path).read_text().split()
    ) == _columnar_seq_oracle(cap)


# -- overlapped collectives + quantized aggregate exchange -------------

_GX_PACED_FLOW = '''
import os

import bytewax_tpu.operators as op
from bytewax_tpu import xla
from bytewax_tpu.dataflow import Dataflow
from bytewax_tpu.connectors.files import FileSink
from bytewax_tpu.inputs import DynamicSource, StatelessSourcePartition


class _Part(StatelessSourcePartition):
    """Paced batches so the run spans several epochs (several
    collective flush rounds), not one EOF burst."""

    def __init__(self, worker_index):
        import time

        base = worker_index * 1000
        self._sleep = float(os.environ.get("GX_PACE_S", "0"))
        self._time = time
        # GX_HOLD_CLOSES=N: hold EOF (empty polls) until this process
        # has really closed N epochs — chaos runs use it so an
        # epoch-pinned injector can never race EOF / the first flush
        # (wall-clock capped so a stalled run still ends).
        self._hold = int(os.environ.get("GX_HOLD_CLOSES", "0"))
        self._hold_deadline = time.monotonic() + 60
        # GX_INTS=1: ship plain ints so every aggregate column stays
        # on the exact (integer) path — the bit-for-bit oracle runs.
        ints = os.environ.get("GX_INTS", "0") == "1"
        self._batches = [
            [
                (
                    f"k{{i % 7}}",
                    (base + b * 100 + i)
                    if ints
                    else float(base + b * 100 + i),
                )
                for i in range(100)
            ]
            for b in range(int(os.environ.get("GX_BATCHES", "4")))
        ]

    def next_batch(self):
        if not self._batches:
            if self._hold:
                from bytewax_tpu.engine.flight import RECORDER

                closes = RECORDER.counters.get("epoch_close_count", 0)
                if (
                    closes < self._hold
                    and self._time.monotonic() < self._hold_deadline
                ):
                    self._time.sleep(0.05)
                    return []
            raise StopIteration()
        if self._sleep:
            self._time.sleep(self._sleep)
        return self._batches.pop(0)


class Src(DynamicSource):
    def build(self, step_id, worker_index, worker_count):
        return _Part(worker_index)


flow = Dataflow("gx_paced_df")
s = op.input("inp", flow, Src())
st = xla.stats_final("stats", s)
fmt = op.map(
    "fmt",
    st,
    lambda kv: (
        kv[0],
        f"{{kv[0]}};{{kv[1][0]}};{{kv[1][1]:.6f}};{{kv[1][2]}};{{kv[1][3]}}",
    ),
)
vals = op.map_value("val", fmt, lambda v: v)
op.output("out", vals, FileSink({out_path!r}))
'''


def _gx_paced_oracle(batches=4):
    rows = {}
    for base in (0, 1000):
        for b in range(batches):
            for i in range(100):
                rows.setdefault(f"k{i % 7}", []).append(
                    float(base + b * 100 + i)
                )
    return {
        k: (min(g), sum(g) / len(g), max(g), len(g))
        for k, g in rows.items()
    }


def _run_gx_paced(tmp_path, name, extra_env, timeout=240):
    flow_py = tmp_path / f"{name}.py"
    out_path = str(tmp_path / f"{name}_out.txt")
    flow_py.write_text(_GX_PACED_FLOW.format(out_path=out_path))
    env = _env()
    env["BYTEWAX_TPU_ACCEL"] = "1"
    env["BYTEWAX_TPU_DISTRIBUTED"] = "1"
    env["BYTEWAX_TPU_GLOBAL_EXCHANGE"] = "1"
    env["BYTEWAX_TPU_GLOBAL_EXCHANGE_DEBUG"] = "1"
    # Keep ingest batch-granular: the coalescer would swallow the
    # whole paced source inside one poll and collapse the run into a
    # single EOF flush — these tests need SEVERAL epoch-close rounds.
    env["BYTEWAX_TPU_INGEST_TARGET_ROWS"] = "0"
    env.update(extra_env)
    res = subprocess.run(
        [
            sys.executable,
            "-m",
            "bytewax_tpu.testing",
            f"{flow_py}:flow",
            "-p",
            "2",
            "-s",
            "0.2",
        ],
        env=env,
        cwd=tmp_path,
        capture_output=True,
        text=True,
        timeout=timeout,
    )
    assert res.returncode == 0, res.stderr[-3000:]
    got = {}
    for line in Path(out_path).read_text().split():
        key, mn, mean, mx, count = line.split(";")
        assert key not in got, f"key {key} emitted twice"
        got[key] = (float(mn), float(mean), float(mx), int(count))
    return got, res.stderr


def test_cluster_gsync_overlap_matches_lockstep_and_oracle(tmp_path):
    """BYTEWAX_TPU_GSYNC_OVERLAP=1: the sealed exchange runs on the
    collective lane one epoch behind the compute frontier, and the
    final output is BYTE-IDENTICAL to the lock-step tier and the
    host oracle — overlap changes when the collective runs, never
    what it computes (docs/performance.md "Overlapped
    collectives")."""
    env = {"GX_PACE_S": "0.12", "GX_BATCHES": "4"}
    lockstep, _ = _run_gx_paced(
        tmp_path, "gx_lockstep", dict(env, BYTEWAX_TPU_GSYNC_OVERLAP="0")
    )
    overlap, stderr = _run_gx_paced(
        tmp_path, "gx_overlap", dict(env, BYTEWAX_TPU_GSYNC_OVERLAP="1")
    )
    # Both processes sealed collective rounds (several epochs).
    assert stderr.count("global-exchange: proc 0 flushed") >= 1
    assert stderr.count("global-exchange: proc 1 flushed") >= 1
    assert overlap == lockstep
    oracle = _gx_paced_oracle()
    assert set(overlap) == set(oracle)
    for k, (mn, mean, mx, count) in oracle.items():
        assert overlap[k][0] == mn and overlap[k][2] == mx
        assert overlap[k][3] == count
        assert abs(overlap[k][1] - mean) < 1e-6


@pytest.mark.parametrize("quant", ["int8", "bf16"])
def test_cluster_gsync_quant_bounds_and_exact_counts(tmp_path, quant):
    """BYTEWAX_TPU_GSYNC_QUANT: the quantized partial exchange
    produces counts EXACTLY equal to the exact tier's and floats
    within the codec's documented bounds — composed with overlap or
    not.  (The two runs are not compared to each other: the
    epoch-boundary split of rows across flush rounds is wall-clock
    dependent, so per-round quantization error differs run to run;
    the invariants are the bounds and the exact counts.)"""
    env = {"GX_PACE_S": "0.1", "GX_BATCHES": "3"}
    quant_env = dict(env, BYTEWAX_TPU_GSYNC_QUANT=quant)
    got, _ = _run_gx_paced(tmp_path, f"gx_{quant}", quant_env)
    both, _ = _run_gx_paced(
        tmp_path,
        f"gx_{quant}_ovl",
        dict(quant_env, BYTEWAX_TPU_GSYNC_OVERLAP="1"),
    )
    oracle = _gx_paced_oracle(batches=3)
    assert set(got) == set(oracle)
    assert set(both) == set(oracle)
    for k, (mn, mean, mx, count) in oracle.items():
        gmn, gmean, gmx, gcount = got[k]
        assert gcount == count  # counts exact, always
        assert both[k][3] == count  # under overlap too
        # min/max partials: one value per key per flush round, so
        # the error never accumulates — bounded by one quantization
        # step of the block max (values span up to ~1400).
        tol = (1400.0 / 254.0) if quant == "int8" else 1400.0 * 2.0**-8
        assert abs(gmn - mn) <= tol, (k, quant)
        assert abs(gmx - mx) <= tol, (k, quant)
        # sum partials accumulate one quantization error per flush
        # round, and the epoch split is timing-dependent — assert a
        # loose-but-meaningful relative bound on the mean (the exact
        # per-round bound is pinned by the codec property test in
        # tests/test_wire.py).
        assert abs(gmean - mean) <= 0.05 * max(abs(mean), 1.0), (
            k,
            quant,
        )


def test_cluster_gsync_quant_divergence_fails_typed(tmp_path):
    """A cluster where processes disagree on the quant mode must
    fail loudly at the first flush (the mode rides the round
    payload), never desynchronize the round sequence."""
    flow_py = tmp_path / "gx_div.py"
    out_path = str(tmp_path / "gx_div_out.txt")
    flow_py.write_text(_GX_PACED_FLOW.format(out_path=out_path))
    spawn_py = tmp_path / "spawn_div.py"
    spawn_py.write_text(
        '''
import os, subprocess, sys, socket

def free_port():
    s = socket.socket(); s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]; s.close(); return p

addrs = ";".join(f"127.0.0.1:{free_port()}" for _ in range(2))
procs = []
for pid, quant in ((0, "int8"), (1, "off")):
    env = dict(os.environ)
    env["BYTEWAX_TPU_GSYNC_QUANT"] = quant
    procs.append(subprocess.Popen(
        [sys.executable, "-m", "bytewax_tpu.run",
         sys.argv[1] + ":flow", "-a", addrs, "-i", str(pid),
         "-s", "0.2"],
        env=env, stderr=subprocess.PIPE, text=True,
    ))
errs = [p.communicate(timeout=150)[1] for p in procs]
codes = [p.returncode for p in procs]
sys.stderr.write("\\n".join(errs))
sys.exit(0 if any(c != 0 for c in codes) else 3)
'''
    )
    env = _env()
    env["BYTEWAX_TPU_ACCEL"] = "1"
    env["BYTEWAX_TPU_DISTRIBUTED"] = "1"
    env["GX_BATCHES"] = "2"
    res = subprocess.run(
        [sys.executable, str(spawn_py), str(flow_py)],
        env=env,
        cwd=tmp_path,
        capture_output=True,
        text=True,
        timeout=200,
    )
    assert res.returncode == 0, (res.returncode, res.stderr[-3000:])
    assert "disagree on BYTEWAX_TPU_GSYNC_QUANT" in res.stderr


@pytest.mark.parametrize("depth", [2, 4])
def test_cluster_gsync_depth_ladder_matches_lockstep_and_oracle(
    tmp_path, depth
):
    """BYTEWAX_TPU_GSYNC_DEPTH=D: up to D sealed rounds ride the
    collective lane behind the compute frontier, retired in order —
    and the final output is BYTE-IDENTICAL to the lock-step tier and
    the host oracle at every rung of the ladder (depth 1 is the
    original double-buffered overlap, pinned by
    test_cluster_gsync_overlap_matches_lockstep_and_oracle)."""
    env = {"GX_PACE_S": "0.12", "GX_BATCHES": "4"}
    lockstep, _ = _run_gx_paced(
        tmp_path,
        f"gx_d{depth}_lockstep",
        dict(env, BYTEWAX_TPU_GSYNC_OVERLAP="0"),
    )
    laddered, stderr = _run_gx_paced(
        tmp_path,
        f"gx_d{depth}",
        dict(
            env,
            BYTEWAX_TPU_GSYNC_OVERLAP="1",
            BYTEWAX_TPU_GSYNC_DEPTH=str(depth),
        ),
    )
    assert stderr.count("global-exchange: proc 0 flushed") >= 1
    assert stderr.count("global-exchange: proc 1 flushed") >= 1
    assert laddered == lockstep
    oracle = _gx_paced_oracle()
    assert set(laddered) == set(oracle)
    for k, (mn, mean, mx, count) in oracle.items():
        assert laddered[k][0] == mn and laddered[k][2] == mx
        assert laddered[k][3] == count
        assert abs(laddered[k][1] - mean) < 1e-6


def test_cluster_gsync_quant_device_merge_matches_host_fold(tmp_path):
    """The device-side dequant+merge (engine/xla.py agg_merge_fn)
    against the host-fold oracle (the BYTEWAX_TPU_WIRE=pickle-era
    fallback, which pins _merge_demoted): on an all-integer workload
    every aggregate column rides the exact path, so the two folds —
    int32 device tables vs the host float64 fold — must agree BIT
    FOR BIT, and both must equal the host oracle exactly (float
    columns are only bound-compared elsewhere: their per-round
    quantization error is wall-clock round-split dependent)."""
    env = {
        "GX_PACE_S": "0.1",
        "GX_BATCHES": "3",
        "GX_INTS": "1",
        "BYTEWAX_TPU_GSYNC_QUANT": "int8",
        "BYTEWAX_TPU_GSYNC_OVERLAP": "1",
    }
    device, _ = _run_gx_paced(tmp_path, "gx_devmerge", env)
    host, _ = _run_gx_paced(
        tmp_path,
        "gx_hostmerge",
        dict(env, BYTEWAX_TPU_WIRE="pickle"),
    )
    assert device == host
    oracle = _gx_paced_oracle(batches=3)
    assert set(device) == set(oracle)
    for k, (mn, mean, mx, count) in oracle.items():
        assert device[k][0] == mn and device[k][2] == mx
        assert device[k][3] == count
        assert abs(device[k][1] - mean) < 1e-9


def test_gsync_overlap_knob_inert_without_global_mesh(
    entry_point, tmp_path, monkeypatch
):
    """Overlap/quant only renegotiate the cluster-spanning collective
    tier: under all three in-process entry points (no global mesh)
    the knobs are inert and a keyed aggregation equals the host
    oracle bit for bit."""
    import bytewax_tpu.operators as op
    from bytewax_tpu import xla as bxla
    from bytewax_tpu.dataflow import Dataflow
    from bytewax_tpu.testing import TestingSink, TestingSource
    from datetime import timedelta

    monkeypatch.setenv("BYTEWAX_TPU_GSYNC_OVERLAP", "1")
    monkeypatch.setenv("BYTEWAX_TPU_GSYNC_QUANT", "int8")
    from bytewax_tpu.engine import wire as _wire

    _wire.reconfigure()
    items = [(f"k{i % 5}", float(i)) for i in range(200)]
    out = []
    flow = Dataflow("gsync_inert_df")
    s = op.input("inp", flow, TestingSource(items, batch_size=16))
    summed = op.reduce_final("sum", s, bxla.SUM)
    op.output("out", summed, TestingSink(out))
    entry_point(flow, epoch_interval=timedelta(seconds=0))
    _wire.reconfigure()
    oracle = {}
    for k, v in items:
        oracle[k] = oracle.get(k, 0.0) + v
    assert dict(out) == oracle
