"""Streaming-inference subsystem tests (``op.infer``,
docs/inference.md): device-tier scoring against the host numpy
oracle, broadcast-params recovery, hot swap at the agreed epoch
close, exactly-once across a supervised restart, demotion, and the
``POST /model`` control plane.

Faults are injected ONLY through the engine's own injector
(``BYTEWAX_TPU_FAULTS``) — never by monkeypatching engine internals.
"""

import json
import math
import os
import subprocess
import sys
import urllib.error
import urllib.request
from collections import defaultdict
from datetime import timedelta
from pathlib import Path

import numpy as np
import pytest

import bytewax_tpu.operators as op
from bytewax_tpu.dataflow import Dataflow
from bytewax_tpu.engine import driver as engine_driver
from bytewax_tpu.engine import faults, flight
from bytewax_tpu.recovery import RecoveryConfig, init_db_dir
from bytewax_tpu.testing import TestingSink, TestingSource, run_main

ZERO_TD = timedelta(seconds=0)


@pytest.fixture(autouse=True)
def _fresh_engine_state():
    """No pending params update or spent fault counters may leak
    between tests (both are module-level by design — that survival is
    the exactly-once mechanism under supervised restarts)."""
    faults.reset()
    engine_driver.reset_params_update()
    yield
    faults.reset()
    engine_driver.reset_params_update()


def _linear_apply(params, x):
    # Works unchanged under jit (jax arrays) and numpy (host tier).
    return x[:, 0] * params["w"] + params["b"]


# -- oracle parity under every entry point ------------------------------


def test_infer_matches_host_oracle(entry_point):
    inp = [(f"k{i % 5}", float(i)) for i in range(40)]
    out = []
    flow = Dataflow("infer_parity_df")
    s = op.input("inp", flow, TestingSource(inp, batch_size=8))
    s = op.infer(
        "score",
        s,
        _linear_apply,
        {"w": np.float32(3.0), "b": np.float32(1.0)},
    )
    op.output("out", s, TestingSink(out))
    entry_point(flow, epoch_interval=ZERO_TD)
    # 3*i + 1 is exact in float32 for this range, so the device path
    # must equal the oracle bit-for-bit.
    want = sorted((k, v * 3.0 + 1.0) for k, v in inp)
    assert sorted(out) == want


def test_infer_multi_feature_tuple_output(entry_point):
    def apply(params, x):
        base = x[:, 0] * params["w"][0] + x[:, 1] * params["w"][1]
        return base, base * 2.0

    inp = [(f"k{i % 3}", (float(i), float(i % 5))) for i in range(30)]
    out = []
    flow = Dataflow("infer_multi_df")
    s = op.input("inp", flow, TestingSource(inp, batch_size=6))
    s = op.infer(
        "score", s, apply, {"w": np.array([2.0, 4.0], np.float32)}
    )
    op.output("out", s, TestingSink(out))
    entry_point(flow, epoch_interval=ZERO_TD)
    want = sorted(
        (k, (a * 2.0 + b * 4.0, (a * 2.0 + b * 4.0) * 2.0))
        for k, (a, b) in inp
    )
    assert sorted(out) == want


def test_infer_host_knob_forces_host_apply(monkeypatch):
    # BYTEWAX_TPU_INFER_DEVICE=0 must route scoring through
    # host_apply without touching the device tier at all: an apply_fn
    # that cannot be traced proves the jitted path never runs.
    monkeypatch.setenv("BYTEWAX_TPU_INFER_DEVICE", "0")

    def poisoned_apply(params, x):  # pragma: no cover - must not run
        raise AssertionError("device apply ran with the knob off")

    def host_apply(params, x):
        return x[:, 0] * params["w"] + params["b"]

    inp = [(f"k{i % 3}", float(i)) for i in range(12)]
    out = []
    flow = Dataflow("infer_hostknob_df")
    s = op.input("inp", flow, TestingSource(inp, batch_size=4))
    s = op.infer(
        "score",
        s,
        poisoned_apply,
        {"w": np.float32(5.0), "b": np.float32(2.0)},
        host_apply=host_apply,
    )
    op.output("out", s, TestingSink(out))
    run_main(flow, epoch_interval=ZERO_TD)
    assert sorted(out) == sorted((k, v * 5.0 + 2.0) for k, v in inp)


# -- the anomaly-detector port ------------------------------------------


def test_anomaly_infer_flow_matches_bespoke_oracle():
    # The op.infer port of the anomaly detector must reproduce the
    # bespoke stateful_map flow's per-key output streams: values and
    # anomaly flags exactly, z within float32 tolerance of the
    # float64 host oracle (the input keeps every |z| far from the
    # threshold boundary, so flags cannot flap on rounding).
    import random

    from bytewax_tpu.models.anomaly import anomaly_infer_flow
    from bytewax_tpu.xla import zscore

    random.seed(7)
    items = [
        (k, random.gauss(0.0, 1.0))
        for _ in range(60)
        for k in ("a", "b", "c")
    ]
    items[100] = ("a", 40.0)  # an unambiguous anomaly

    states = {}
    oracle = defaultdict(list)
    mapper = zscore(2.5)
    for k, v in items:
        states[k], scored = mapper(states.get(k), v)
        oracle[k].append(scored)

    out = []
    run_main(
        anomaly_infer_flow(
            TestingSource(list(items)), TestingSink(out), threshold=2.5
        ),
        epoch_interval=ZERO_TD,
    )
    got = defaultdict(list)
    for k, v in out:
        got[k].append(v)
    assert got.keys() == oracle.keys()
    for k in oracle:
        assert len(got[k]) == len(oracle[k])
        for (vo, zo, ao), (vg, zg, ag) in zip(oracle[k], got[k]):
            assert math.isclose(vo, vg, rel_tol=1e-6, abs_tol=1e-6)
            assert abs(zo - zg) <= 1e-3 * max(1.0, abs(zo)), (k, zo, zg)
            assert ao == ag, (k, vo, zo, ao, ag)
    assert sum(1 for vs in oracle.values() for (_, _, a) in vs if a) > 0


# -- broadcast-params recovery ------------------------------------------


def _count_feats(state, value):
    n = (state or 0) + 1
    return n, (float(value), float(n))


def _count_apply(params, x):
    return x[:, 0] * params["w"] + x[:, 1]


def test_infer_resume_restores_params_and_keyed_state(recovery_config):
    # Run 1 swaps w 10 -> 20 at its first close; run 2 resumes and
    # must score with the swapped generation AND the per-key count
    # state from the upstream stateful_map — recovery covers the
    # broadcast params and the keyed state together.
    inp = [("a", 1.0), ("a", 2.0), TestingSource.EOF(), ("a", 3.0)]

    def build(out):
        flow = Dataflow("infer_resume_df")
        s = op.input("inp", flow, TestingSource(inp, batch_size=1))
        s = op.stateful_map("count", s, _count_feats)
        s = op.infer("score", s, _count_apply, {"w": np.float32(10.0)})
        op.output("out", s, TestingSink(out))
        return flow

    engine_driver.update_params({"w": np.float32(20.0)})
    out = []
    run_main(
        build(out),
        epoch_interval=ZERO_TD,
        recovery_config=recovery_config,
    )
    # Epoch 1 scores with the initial params (the swap lands at the
    # close, after the delivery); epoch 2 scores with the new ones.
    assert out == [("a", 1.0 * 10.0 + 1.0), ("a", 2.0 * 20.0 + 2.0)]

    # Resume: no pending update this run — the swapped generation and
    # the count state must come back from the store.
    out2 = []
    run_main(
        build(out2),
        epoch_interval=ZERO_TD,
        recovery_config=recovery_config,
    )
    assert out2 == [("a", 3.0 * 20.0 + 3.0)]


# -- hot swap at the agreed close ---------------------------------------


def test_infer_hot_swap_lands_at_epoch_close(entry_point, monkeypatch):
    monkeypatch.setenv("BYTEWAX_FLIGHT_RECORDER", "1")
    inp = [
        ("a", 1.0),
        ("a", 2.0),
        TestingSource.PAUSE(timedelta(milliseconds=50)),
        ("a", 3.0),
        ("a", 4.0),
    ]
    out = []
    flow = Dataflow("infer_swap_df")
    s = op.input("inp", flow, TestingSource(inp, batch_size=2))
    s = op.infer(
        "score",
        s,
        lambda p, x: x[:, 0] * p["w"],
        {"w": np.float32(10.0)},
    )
    op.output("out", s, TestingSink(out))

    swaps_before = flight.RECORDER.counters.get("params_swap_count", 0)
    digest = engine_driver.update_params({"w": np.float32(100.0)})
    assert isinstance(digest, str) and len(digest) == 16
    entry_point(flow, epoch_interval=ZERO_TD)

    # The PAUSE spans an epoch close: the first batch scores with the
    # old params, everything after the agreed close with the new.
    assert out == [
        ("a", 10.0),
        ("a", 20.0),
        ("a", 300.0),
        ("a", 400.0),
    ]
    assert (
        flight.RECORDER.counters.get("params_swap_count", 0)
        == swaps_before + 1
    )
    swaps = [
        e for e in flight.RECORDER.tail() if e["kind"] == "params_swap"
    ]
    assert swaps and swaps[-1]["digest"] == digest


def test_infer_swap_targets_step_by_id(monkeypatch):
    # update_params(step_id=...) accepts the user-level step id and
    # must swap exactly that step, leaving others untouched.
    monkeypatch.setenv("BYTEWAX_FLIGHT_RECORDER", "1")
    inp = [
        ("a", 1.0),
        TestingSource.PAUSE(timedelta(milliseconds=50)),
        ("a", 2.0),
    ]
    out = []
    flow = Dataflow("infer_target_df")
    s = op.input("inp", flow, TestingSource(inp, batch_size=1))
    s = op.infer(
        "score", s, lambda p, x: x[:, 0] * p["w"], {"w": np.float32(10.0)}
    )
    s = op.infer(
        "score2", s, lambda p, x: x[:, 0] * p["w"], {"w": np.float32(2.0)}
    )
    op.output("out", s, TestingSink(out))
    engine_driver.update_params(
        {"w": np.float32(100.0)}, step_id="infer_target_df.score"
    )
    run_main(flow, epoch_interval=ZERO_TD)
    # Item 1 scores 1*10*2; item 2 scores with only "score" swapped:
    # 2*100*2.
    assert out == [("a", 20.0), ("a", 400.0)]


def test_infer_swap_structure_mismatch_rejected(monkeypatch):
    # A pending tree that does not match the incumbent structure must
    # be rejected deterministically at the close: generation stays,
    # scores stay, and the rejection lands in the flight ring.
    monkeypatch.setenv("BYTEWAX_FLIGHT_RECORDER", "1")
    inp = [
        ("a", 1.0),
        TestingSource.PAUSE(timedelta(milliseconds=50)),
        ("a", 2.0),
    ]
    out = []
    flow = Dataflow("infer_reject_df")
    s = op.input("inp", flow, TestingSource(inp, batch_size=1))
    s = op.infer(
        "score", s, lambda p, x: x[:, 0] * p["w"], {"w": np.float32(10.0)}
    )
    op.output("out", s, TestingSink(out))
    engine_driver.update_params({"not_w": np.float32(999.0)})
    run_main(flow, epoch_interval=ZERO_TD)
    assert out == [("a", 10.0), ("a", 20.0)]
    rejected = [
        e
        for e in flight.RECORDER.tail()
        if e["kind"] == "params_swap_rejected"
    ]
    assert rejected


# -- exactly-once across a supervised restart ---------------------------


def test_infer_swap_exactly_once_across_supervised_restart(
    entry_point, tmp_path, monkeypatch
):
    # An injected crash at the pinned params_swap site — fired at the
    # agreed close BEFORE any runtime installs and BEFORE the pending
    # target is consumed — unwinds the worker; the supervisor
    # restarts it, the module-level target survives, and the swap
    # lands exactly once at the replayed close.  Output must equal a
    # fault-free run's (the sink truncates the torn epoch).
    from bytewax_tpu.connectors.files import FileSink

    monkeypatch.setenv("BYTEWAX_TPU_FAULTS", "params_swap:crash:1:x1")
    monkeypatch.setenv("BYTEWAX_TPU_MAX_RESTARTS", "2")
    monkeypatch.setenv("BYTEWAX_TPU_RESTART_BACKOFF_S", "0.05")
    monkeypatch.setenv("BYTEWAX_FLIGHT_RECORDER", "1")

    inp = [
        ("a", 1.0),
        ("a", 2.0),
        TestingSource.PAUSE(timedelta(milliseconds=100)),
        ("a", 3.0),
    ]
    out_path = tmp_path / "out.txt"
    db = tmp_path / "db"
    db.mkdir()
    init_db_dir(db, 1)

    flow = Dataflow("infer_chaos_df")
    s = op.input("inp", flow, TestingSource(inp, batch_size=1))
    s = op.infer(
        "score", s, lambda p, x: x[:, 0] * p["w"], {"w": np.float32(10.0)}
    )
    s = op.map("fmt", s, lambda kv: (kv[0], f"{kv[0]}={kv[1]}"))
    op.output("out", s, FileSink(str(out_path)))

    restarts_before = flight.RECORDER.counters.get(
        "worker_restart_count", 0
    )
    swaps_before = flight.RECORDER.counters.get("params_swap_count", 0)
    engine_driver.update_params({"w": np.float32(20.0)})
    entry_point(
        flow,
        epoch_interval=ZERO_TD,
        recovery_config=RecoveryConfig(str(db)),
    )
    assert (
        flight.RECORDER.counters.get("worker_restart_count", 0)
        == restarts_before + 1
    )
    # Exactly once: the crash fired before install AND consume, so
    # the restarted close swaps a single time — never zero (the
    # target died with the crash) and never twice (the target was
    # consumed pre-crash and re-applied).
    assert (
        flight.RECORDER.counters.get("params_swap_count", 0)
        == swaps_before + 1
    )
    # Every item scores exactly once, and the single agreed swap
    # splits the per-key timeline exactly once: item 1 committed
    # pre-swap (the crash fired before any install), and no item may
    # score with the old generation after one scored with the new.
    # WHICH close the replayed items land under is emergent restart
    # timing — epoch boundaries are not part of the contract here.
    lines = out_path.read_text().split()
    assert len(lines) == 3
    gens = [
        float(line.split("=")[1]) / (i + 1.0)
        for i, line in enumerate(lines)
    ]
    assert gens[0] == 10.0
    assert all(w in (10.0, 20.0) for w in gens)
    assert gens == sorted(gens)


# -- demotion carries the swapped generation ----------------------------


def test_infer_demotion_preserves_swapped_params(monkeypatch):
    # Epoch 1 scores on device and the close swaps the params; from
    # epoch 2 every device dispatch faults, so the step demotes to
    # host_apply — which must score with the SWAPPED generation (the
    # demotion snapshot carries the params, BTX-SNAPSHOT pairing).
    monkeypatch.setenv("BYTEWAX_TPU_FAULTS", "device_dispatch:error:2+")
    monkeypatch.setenv("BYTEWAX_TPU_DEMOTE_AFTER", "2")
    monkeypatch.setenv("BYTEWAX_TPU_INGEST_TARGET_ROWS", "0")
    monkeypatch.setenv("BYTEWAX_FLIGHT_RECORDER", "1")

    def host_apply(params, x):
        return x[:, 0] * params["w"]

    inp = [("a", float(i)) for i in range(1, 13)]
    out = []
    flow = Dataflow("infer_demote_df")
    s = op.input("inp", flow, TestingSource(inp, batch_size=4))
    s = op.infer(
        "score",
        s,
        lambda p, x: x[:, 0] * p["w"],
        {"w": np.float32(10.0)},
        host_apply=host_apply,
    )
    op.output("out", s, TestingSink(out))
    engine_driver.update_params({"w": np.float32(20.0)})
    run_main(flow, epoch_interval=ZERO_TD)

    events = [
        e for e in flight.RECORDER.tail() if e["kind"] == "demotion"
    ]
    assert events and events[-1]["step"].startswith(
        "infer_demote_df.score"
    )
    # Batch 1 on device with w=10; batches 2-3 post-swap (w=20), the
    # later ones scored by host_apply after the demotion.
    want = [("a", float(i) * 10.0) for i in range(1, 5)] + [
        ("a", float(i) * 20.0) for i in range(5, 13)
    ]
    assert out == want


# -- 2-process cluster: the swap commits at one agreed close ------------


_CLUSTER_FLOW = '''
import os
from datetime import datetime, timedelta, timezone

import numpy as np

import bytewax_tpu.operators as op
from bytewax_tpu.dataflow import Dataflow
from bytewax_tpu.connectors.files import FileSink
from bytewax_tpu.engine import driver as engine_driver
from bytewax_tpu.inputs import DynamicSource, StatelessSourcePartition


class _Part(StatelessSourcePartition):
    def __init__(self, worker_index):
        self._w = worker_index
        self._sent = 0
        self._resume_at = None

    def next_batch(self):
        now = datetime.now(timezone.utc)
        if self._sent == 0:
            self._sent = 1
            # Pause NON-blocking via next_awake so several epoch
            # closes run between the two batches — the agreed swap
            # must commit in that window on every process.
            self._resume_at = now + timedelta(seconds=0.8)
            return [(f"w{self._w}", 1.0)]
        if self._sent == 1:
            if now < self._resume_at:
                return []
            self._sent = 2
            return [(f"w{self._w}", 2.0)]
        raise StopIteration()

    def next_awake(self):
        return self._resume_at if self._sent == 1 else None


class PerWorkerSource(DynamicSource):
    def build(self, step_id, worker_index, worker_count):
        return _Part(worker_index)


# Every process records the same pending update at startup; the swap
# itself must land at one cluster-agreed epoch close.
engine_driver.update_params({"w": np.float32(100.0)})

flow = Dataflow("cluster_infer_df")
s = op.input("inp", flow, PerWorkerSource())
s = op.infer(
    "score", s, lambda p, x: x[:, 0] * p["w"], {"w": np.float32(10.0)}
)
s = op.map("fmt", s, lambda kv: (kv[0], f"{kv[0]}={kv[1]}"))
op.output("out", s, FileSink(@OUT_PATH@))
'''


@pytest.mark.slow
def test_cluster_2proc_swap_agreed_close(tmp_path):
    out_path = str(tmp_path / "out.txt")
    flow_py = tmp_path / "cluster_infer_flow.py"
    flow_py.write_text(_CLUSTER_FLOW.replace("@OUT_PATH@", repr(out_path)))
    env = dict(os.environ)
    env["PYTHONPATH"] = (
        "/root/repo" + os.pathsep + env.get("PYTHONPATH", "")
    )
    env["BYTEWAX_TPU_PLATFORM"] = "cpu"
    res = subprocess.run(
        [
            sys.executable,
            "-m",
            "bytewax_tpu.testing",
            f"{flow_py}:flow",
            "-p",
            "2",
            "-s",
            "0.1",
        ],
        env=env,
        cwd=tmp_path,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert res.returncode == 0, res.stderr[-2000:]
    lines = sorted(Path(out_path).read_text().split())
    # Every worker's first item scored pre-swap and its second item
    # post-swap: the swap landed at one agreed close on BOTH
    # processes (a one-sided swap would leave a w*=10.0 second item).
    assert lines == ["w0=10.0", "w0=200.0", "w1=10.0", "w1=200.0"]


# -- POST /model control plane ------------------------------------------


def _tiny_flow():
    flow = Dataflow("model_api_df")
    s = op.input("inp", flow, TestingSource([("a", 1.0)]))
    s = op.infer(
        "score", s, lambda p, x: x[:, 0] * p["w"], {"w": np.float32(1.0)}
    )
    op.output("out", s, TestingSink([]))
    return flow


def test_webserver_model_endpoint(tmp_path, monkeypatch):
    # POST /model records the pending update through model_fn and
    # answers the digest; malformed bodies are a 400, not a 500; and
    # without a model_fn the path stays a 404 (no new surface).
    monkeypatch.chdir(tmp_path)
    monkeypatch.setenv("BYTEWAX_DATAFLOW_API_ENABLED", "1")
    monkeypatch.setenv("BYTEWAX_DATAFLOW_API_PORT", "0")
    from bytewax_tpu.engine.webserver import maybe_start_server

    srv = maybe_start_server(
        _tiny_flow(),
        model_fn=lambda params, step_id=None: engine_driver.update_params(
            params, step_id, source="http"
        ),
    )
    assert srv is not None
    base = f"http://127.0.0.1:{srv.port}"
    try:
        body = json.dumps(
            {"params": {"w": 42.0}, "step_id": "model_api_df.score"}
        ).encode()
        req = urllib.request.Request(
            base + "/model", data=body, method="POST"
        )
        with urllib.request.urlopen(req, timeout=5) as rsp:
            payload = json.loads(rsp.read())
        assert payload["accepted"] is True
        assert isinstance(payload["digest"], str)
        pending = engine_driver._pending_params()
        assert pending is not None
        assert pending[0] == "model_api_df.score"
        assert pending[1] == payload["digest"]

        # A body without a params pytree records nothing.
        req = urllib.request.Request(
            base + "/model", data=b"{}", method="POST"
        )
        with pytest.raises(urllib.error.HTTPError) as exc_info:
            urllib.request.urlopen(req, timeout=5)
        assert exc_info.value.code == 400
    finally:
        srv.shutdown()

    srv = maybe_start_server(_tiny_flow())
    assert srv is not None
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/model",
            data=b'{"params": {"w": 1.0}}',
            method="POST",
        )
        with pytest.raises(urllib.error.HTTPError) as exc_info:
            urllib.request.urlopen(req, timeout=5)
        assert exc_info.value.code == 404
    finally:
        srv.shutdown()


def test_webserver_model_requires_loopback_opt_in(tmp_path, monkeypatch):
    # Same guard as POST /stop: on a non-loopback bind the endpoint
    # is disabled unless BYTEWAX_TPU_ALLOW_REMOTE_STOP=1 — any
    # network peer could otherwise re-model the cluster.
    monkeypatch.chdir(tmp_path)
    monkeypatch.setenv("BYTEWAX_DATAFLOW_API_ENABLED", "1")
    monkeypatch.setenv("BYTEWAX_DATAFLOW_API_PORT", "0")
    monkeypatch.setenv("BYTEWAX_DATAFLOW_API_HOST", "0.0.0.0")
    from bytewax_tpu.engine.webserver import maybe_start_server

    got = []
    srv = maybe_start_server(
        _tiny_flow(), model_fn=lambda p, s=None: got.append(p) or "x"
    )
    assert srv is not None
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/model",
            data=b'{"params": {"w": 1.0}}',
            method="POST",
        )
        with pytest.raises(urllib.error.HTTPError) as exc_info:
            urllib.request.urlopen(req, timeout=5)
        assert exc_info.value.code == 404
        assert got == []
    finally:
        srv.shutdown()


# -- observability ------------------------------------------------------


def test_status_and_graph_carry_infer(entry_point, monkeypatch, tmp_path):
    monkeypatch.setenv("BYTEWAX_DATAFLOW_API_ENABLED", "1")
    monkeypatch.setenv("BYTEWAX_DATAFLOW_API_PORT", "13061")
    monkeypatch.chdir(tmp_path)

    captured = {}

    class _ProbePartition:
        def write_batch(self, items):
            if "status" not in captured:
                with urllib.request.urlopen(
                    "http://127.0.0.1:13061/status", timeout=5
                ) as rsp:
                    captured["status"] = json.loads(rsp.read())
                with urllib.request.urlopen(
                    "http://127.0.0.1:13061/graph", timeout=5
                ) as rsp:
                    captured["graph"] = json.loads(rsp.read())

        def close(self):
            pass

    from bytewax_tpu.outputs import DynamicSink

    class _ProbeSink(DynamicSink):
        def build(self, step_id, worker_index, worker_count):
            return _ProbePartition()

    flow = Dataflow("infer_obs_df")
    s = op.input(
        "inp",
        flow,
        TestingSource([("a", 1.0), ("b", 2.0)], batch_size=2),
    )
    s = op.infer(
        "score", s, lambda p, x: x[:, 0] * p["w"], {"w": np.float32(3.0)}
    )
    op.output("out", s, _ProbeSink())
    entry_point(flow, epoch_interval=ZERO_TD)

    # The probe sink is downstream of the infer step, so by capture
    # time the step exists and has scored the delivered rows.
    infer = captured["status"]["infer"]
    assert len(infer) == 1
    (step_id,), (view,) = zip(*infer.items())
    assert step_id.startswith("infer_obs_df.score")
    assert view["tier"] == "device"
    assert view["generation"] == 0
    assert isinstance(view["digest"], str) and len(view["digest"]) == 16
    assert view["last_swap"] is None

    by_id = {n["step_id"]: n for n in captured["graph"]["steps"]}
    assert by_id[step_id]["tier"] == "device"

    from bytewax_tpu._metrics import generate_python_metrics

    families = generate_python_metrics()
    assert "bytewax_infer_rows_count" in families
    assert "bytewax_infer_params_generation" in families
