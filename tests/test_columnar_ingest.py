"""Columnar zero-copy ingest (docs/performance.md "Columnar ingest"):
batch-native sources, chunked device-side line decode, adaptive
micro-batch coalescing, and bucketed padding.

The host tier (``BYTEWAX_TPU_ACCEL=0`` / plain Python) is the oracle:
a columnar-source run must produce the same output as itemized input,
recovery snapshots taken mid-stream must resume exactly-once across a
tier switch, and the bucketed-padding ladder must bound XLA compiles
however batch lengths churn.
"""

import os
from datetime import datetime, timedelta, timezone

import numpy as np
import pytest

import bytewax_tpu.operators as op
from bytewax_tpu import xla
from bytewax_tpu.connectors.files import CSVSource, FileSource
from bytewax_tpu.dataflow import Dataflow
from bytewax_tpu.engine import batching, flight
from bytewax_tpu.engine.flatten import flatten
from bytewax_tpu.inputs import (
    AbortExecution,
    ColumnarBatch,
    FixedPartitionedSource,
    StatefulSourcePartition,
)
from bytewax_tpu.testing import TestingSink, TestingSource, run_main

ZERO_TD = timedelta(seconds=0)


class _ColumnarPartition(StatefulSourcePartition):
    def __init__(self, batches, resume_state):
        self._batches = batches
        self._idx = 0 if resume_state is None else resume_state

    def next_batch(self):
        if self._idx >= len(self._batches):
            raise StopIteration()
        b = self._batches[self._idx]
        if isinstance(b, TestingSource.ABORT):
            if b._triggered:
                self._idx += 1
                return []
            b._triggered = True
            raise AbortExecution()
        self._idx += 1
        return b

    def snapshot(self) -> int:
        return self._idx


class _ColumnarSource(FixedPartitionedSource):
    """TestingSource analog for prebuilt :class:`ColumnarBatch`es:
    one partition, batch-index snapshots, ``TestingSource.ABORT``
    sentinels honored between batches."""

    def __init__(self, batches):
        self._batches = batches

    def list_parts(self):
        return ["batches"]

    def build_part(self, step_id, for_part, resume_state):
        return _ColumnarPartition(self._batches, resume_state)


def _kv_batches(n_batches, rows, n_keys=8, seed=0):
    """ColumnarBatch({"key", "value"}) batches with int64 values (both
    tiers exact) and every key recurring across batches."""
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n_batches):
        kids = rng.randint(0, n_keys, size=rows)
        out.append(
            ColumnarBatch(
                {
                    "key": np.array([f"k{i}" for i in kids]),
                    "value": rng.randint(0, 100, size=rows).astype(
                        np.int64
                    ),
                }
            )
        )
    return out


def _sum_oracle(batches):
    sums = {}
    for b in batches:
        if isinstance(b, TestingSource.ABORT):
            continue
        for k, v in zip(b.cols["key"].tolist(), b.cols["value"].tolist()):
            sums[k] = sums.get(k, 0) + v
    return sorted(sums.items())


def _sum_flow(flow_id, source, out):
    flow = Dataflow(flow_id)
    s = op.input("inp", flow, source)
    r = op.reduce_final("sum", s, xla.SUM)
    op.output("out", r, TestingSink(out))
    return flow


# -- columnar sources end to end, all 3 entry points ------------------------


def test_columnar_source_matches_host_oracle(
    entry_point, entry_point_name
):
    """A batch-native source's output on the device tier equals the
    per-row oracle under every entry point (multi-lane entry points
    route the batch columnar, without itemizing)."""
    batches = _kv_batches(6, 50)
    c0 = flight.RECORDER.counters.get("ingest_rows_columnar", 0)
    out = []
    entry_point(
        _sum_flow(f"col_eq_{entry_point_name}", _ColumnarSource(batches), out),
        epoch_interval=ZERO_TD,
    )
    assert sorted(out) == _sum_oracle(batches)
    assert (
        flight.RECORDER.counters.get("ingest_rows_columnar", 0) - c0
        == 6 * 50
    )


def test_columnar_cross_tier_recovery(
    entry_point, entry_point_name, recovery_config, monkeypatch
):
    """Abort mid-stream (epoch snapshots land between columnar
    deliveries), resume on the HOST tier: exactly-once equality with
    the unbroken oracle proves the columnar path shares the cross-tier
    snapshot interchange, under every entry point."""
    batches = _kv_batches(8, 25, seed=3)
    inp = batches[:4] + [TestingSource.ABORT()] + batches[4:]
    flow_id = f"col_rec_{entry_point_name}"

    out1 = []
    entry_point(
        _sum_flow(flow_id, _ColumnarSource(inp), out1),
        epoch_interval=ZERO_TD,
        recovery_config=recovery_config,
    )
    # reduce_final only emits at EOF, which the abort preempted.
    assert out1 == []
    out2 = []
    monkeypatch.setenv("BYTEWAX_TPU_ACCEL", "0")
    entry_point(
        _sum_flow(flow_id, _ColumnarSource(inp), out2),
        epoch_interval=ZERO_TD,
        recovery_config=recovery_config,
    )
    assert sorted(out2) == _sum_oracle(batches)


# -- chunked line decode: exact resume at every boundary --------------------


def test_chunked_line_resume_exact_at_every_boundary(tmp_path):
    """Snapshot a columnar FileSource partition after every poll and
    resume a fresh partition from it: prefix + suffix must reproduce
    the file's lines exactly, whatever chunk boundary (including
    mid-line) the snapshot landed on."""
    lines = [f"line-{i}-{'x' * (i % 7)}" for i in range(40)]
    path = tmp_path / "lines.txt"
    path.write_text("\n".join(lines) + "\n")
    src = FileSource(path, columnar=True, chunk_bytes=13)
    (part_name,) = src.list_parts()

    def drain(part):
        got = []
        while True:
            try:
                b = part.next_batch()
            except StopIteration:
                return got
            if len(b):
                got.extend(b.cols["line"].tolist())

    n_polls = 0
    part = src.build_part("inp", part_name, None)
    prefix = []
    while True:
        snap = part.snapshot()
        resumed = src.build_part("inp", part_name, snap)
        assert prefix + drain(resumed) == lines, (
            f"snapshot after poll {n_polls} (offset {snap}) lost or "
            "duplicated lines"
        )
        try:
            b = part.next_batch()
        except StopIteration:
            break
        n_polls += 1
        if len(b):
            prefix.extend(b.cols["line"].tolist())
    assert prefix == lines
    assert n_polls > 10  # chunk_bytes really did split the file up


def test_file_source_columnar_equals_itemized(tmp_path):
    """The columnar (chunked, vectorized-split) file reader feeds a
    device fold to the same result as the itemized per-row reader."""
    rng = np.random.RandomState(1)
    rows = [
        (f"s{rng.randint(6)}", int(rng.randint(0, 50)))
        for _ in range(300)
    ]
    path = tmp_path / "kv.txt"
    path.write_text("".join(f"{k};{v}\n" for k, v in rows))

    def parse(batch):
        from bytewax_tpu.ops.text import split_fields

        cols = split_fields(batch.cols["line"], 2, ";")
        return ColumnarBatch(
            {"key": cols[0], "value": cols[1].astype(np.int64)}
        )

    def run(source, parser=None):
        out = []
        flow = Dataflow("file_col_eq")
        s = op.input("inp", flow, source)
        if parser is not None:
            s = op.flat_map_batch("parse", s, parser)
        else:
            s = op.map(
                "parse",
                s,
                lambda ln: (ln.split(";")[0], int(ln.split(";")[1])),
            )
        r = op.reduce_final("sum", s, xla.SUM)
        op.output("out", r, TestingSink(out))
        run_main(flow, epoch_interval=ZERO_TD)
        return sorted(out)

    columnar = run(
        FileSource(path, columnar=True, chunk_bytes=64), parser=parse
    )
    itemized = run(FileSource(path, batch_size=32))
    oracle = {}
    for k, v in rows:
        oracle[k] = oracle.get(k, 0) + v
    assert columnar == itemized == sorted(oracle.items())


def test_csv_source_columnar_fast_path_and_fallback(tmp_path):
    """Plain CSV takes the vectorized column split (numeric columns
    cast); a batch with quoting falls back to ``csv.DictReader`` and
    arrives itemized — both through the same additive protocol."""
    plain = tmp_path / "plain.csv"
    plain.write_text("name,score\na,1\nb,2\na,3\n")
    out = []
    flow = Dataflow("csv_col")
    s = op.input("inp", flow, CSVSource(plain, columnar=True))
    op.output("out", s, TestingSink(out))
    run_main(flow)
    assert out == [
        {"name": "a", "score": 1.0},
        {"name": "b", "score": 2.0},
        {"name": "a", "score": 3.0},
    ]

    quoted = tmp_path / "quoted.csv"
    quoted.write_text('name,score\n"a,x",1\nb,2\n')
    out = []
    flow = Dataflow("csv_col_quoted")
    s = op.input("inp", flow, CSVSource(quoted, columnar=True))
    op.output("out", s, TestingSink(out))
    run_main(flow)
    assert out == [
        {"name": "a,x", "score": "1"},
        {"name": "b", "score": "2"},
    ]


def test_csv_source_columnar_quoted_embedded_newlines(tmp_path):
    """A quoted field containing newlines parses exactly like itemized
    mode: the fallback feeds terminated lines (csv reassembles the
    multi-line field) and pulls further chunks when a batch ends
    inside an open quote — including a quote spanning a chunk
    boundary."""
    body = 'name,note\na,"line one\nline two"\nb,plain\n'
    path = tmp_path / "multiline.csv"
    path.write_text(body)
    want = [
        {"name": "a", "note": "line one\nline two"},
        {"name": "b", "note": "plain"},
    ]

    def run(chunk_bytes):
        out = []
        flow = Dataflow(f"csv_ml_{chunk_bytes}")
        s = op.input(
            "inp",
            flow,
            CSVSource(path, columnar=True, chunk_bytes=chunk_bytes),
        )
        op.output("out", s, TestingSink(out))
        run_main(flow)
        return out

    assert run(1 << 20) == want  # whole file in one chunk
    # 8-byte chunks force the quoted field across MANY chunk
    # boundaries: the open-quote pull loop must stitch it back.
    assert run(8) == want


def test_csv_source_columnar_refuses_parity_unsound_dialects(tmp_path):
    """Dialects where quote parity doesn't delimit fields (escapechar,
    doublequote=False) can't be chunked safely — a quoted field
    spanning a chunk boundary would be cut mid-row — so columnar mode
    refuses them up front.  QUOTE_NONE has no quoted fields at all, so
    it chunks fine."""
    import csv as _csv

    path = tmp_path / "d.csv"
    path.write_text('h1,h2\na,"x"\n')
    for bad in (
        {"escapechar": "\\"},
        {"doublequote": False},
    ):
        src = CSVSource(path, columnar=True, **bad)
        with pytest.raises(ValueError, match="quote parity"):
            src.build_part("s", src.list_parts()[0], None)

    qn = tmp_path / "qn.csv"
    qn.write_text("h1,h2\na,x\"y\nb,z\n")
    want = None
    for columnar in (False, True):
        out = []
        flow = Dataflow(f"csv_qn_{columnar}")
        s = op.input(
            "inp",
            flow,
            CSVSource(
                qn,
                columnar=columnar,
                chunk_bytes=8,
                quoting=_csv.QUOTE_NONE,
            ),
        )
        op.output("out", s, TestingSink(out))
        run_main(flow)
        if want is None:
            want = out
        assert out == want  # columnar == itemized under QUOTE_NONE


def test_csv_source_columnar_quoted_header_newline(tmp_path):
    """A quoted header field containing a newline parses whole: the
    header read keeps pulling lines while its quote is open, and the
    body offset lands after the full header record."""
    path = tmp_path / "hdr.csv"
    path.write_text('a,"b\nc",d\n1,2,3\n')
    out = []
    flow = Dataflow("csv_hdr_nl")
    s = op.input("inp", flow, CSVSource(path, columnar=True))
    op.output("out", s, TestingSink(out))
    run_main(flow)
    assert out == [{"a": 1.0, "b\nc": 2.0, "d": 3.0}]


def test_csv_source_columnar_sticky_column_types(tmp_path):
    """The numeric-cast decision is made once per run (first fast-path
    batch), so chunk-boundary placement can't flip a column between
    float64 and str: a later chunk with a non-numeric cell in a
    numeric column falls back itemized for that batch only, and
    numeric chunks after it stay float64."""
    path = tmp_path / "sticky.csv"
    path.write_text("k,v\n" + "a,1\n" * 5 + "b,x\n" + "c,2\n")
    out = []
    flow = Dataflow("csv_sticky")
    s = op.input(
        "inp", flow, CSVSource(path, columnar=True, chunk_bytes=12)
    )
    op.output("out", s, TestingSink(out))
    run_main(flow)
    by_key = {}
    for row in out:
        by_key.setdefault(row["k"], []).append(row["v"])
    # Chunks land as: [a,a,a] fast-path float64 · [a,a,b] itemized
    # fallback (whole batch arrives as strings — the documented
    # degradation) · [c] float64 again.  The regression pinned here:
    # no COLUMNAR batch ever carries the column as str, and the batch
    # after the bad cell returns to float64 instead of the dtype
    # sticking wherever the boundary happened to fall.
    assert by_key["a"] == [1.0, 1.0, 1.0, "1", "1"]
    assert by_key["b"] == ["x"]
    assert by_key["c"] == [2.0]


def test_split_fields_byte_lines():
    """``encoding=None`` pipelines hand S-dtype byte lines to the
    field splitter and the numeric cast — both must speak bytes."""
    from bytewax_tpu.ops.text import maybe_numeric, split_fields, split_lines

    lines = split_lines(b"a,1\nb,2\n", encoding=None)
    assert lines.dtype.kind == "S"
    cols = split_fields(lines, 2)
    assert cols is not None
    assert cols[0].tolist() == [b"a", b"b"]
    assert maybe_numeric(cols[1]).tolist() == [1.0, 2.0]
    assert maybe_numeric(np.array([b"007"])).tolist() == [b"007"]


def test_demo_source_mode_mismatch_both_directions():
    """Resuming across RandomMetricSource modes errors clearly BOTH
    ways — the rng state formats (tuple vs numpy dict) are not
    interchangeable."""
    from bytewax_tpu.connectors.demo import RandomMetricSource
    from bytewax_tpu.testing import poll_next_batch

    batch_src = RandomMetricSource(
        "m", interval=ZERO_TD, count=8, seed=1, batch_size=4
    )
    part = batch_src.build_part("demo", "m", None)
    poll_next_batch(part)
    batch_snap = part.snapshot()

    item_src = RandomMetricSource("m", interval=ZERO_TD, count=8, seed=1)
    with pytest.raises(ValueError, match="batch-native"):
        item_src.build_part("demo", "m", batch_snap)

    item_part = item_src.build_part("demo", "m", None)
    poll_next_batch(item_part)
    item_snap = item_part.snapshot()
    with pytest.raises(ValueError, match="itemized"):
        batch_src.build_part("demo", "m", item_snap)


def test_maybe_numeric_round_trip_guard():
    """Numeric-looking strings that don't round-trip stay strings:
    leading-zero identifiers and nan/inf tokens parse as floats but
    say something else."""
    from bytewax_tpu.ops.text import maybe_numeric

    casted = maybe_numeric(np.array(["1", "2.5", "-3"]))
    assert casted.dtype == np.float64
    assert casted.tolist() == [1.0, 2.5, -3.0]
    for cells in (
        ["00501", "10014"],  # zip codes: leading zero lost as float
        ["1", "nan"],
        ["inf", "2"],
        ["a", "1"],  # plain non-numeric
    ):
        kept = maybe_numeric(np.array(cells))
        assert kept.dtype.kind == "U", cells
        assert kept.tolist() == cells
    # "0" and "0.5" round-trip fine.
    assert maybe_numeric(np.array(["0", "0.5"])).tolist() == [0.0, 0.5]


def test_split_lines_ragged_chunk_object_fallback():
    """One huge line sharing a chunk with many short ones must not pad
    every row to the huge width (a 1MB chunk can explode to GBs):
    ragged chunks degrade to an object-dtype per-line split, and the
    CSV consumer still parses them via its fallback."""
    from bytewax_tpu.ops.text import split_fields, split_lines

    short = ["ab"] * 2000
    huge = "x" * 40_000
    body = ("\n".join([*short, huge]) + "\n").encode()
    lines = split_lines(body)
    assert lines.dtype == object
    assert len(lines) == 2001
    assert lines[-1] == huge
    assert lines[0] == "ab"
    # split_fields declines object arrays (the caller's csv fallback
    # takes over) instead of crashing in np.char.
    assert split_fields(lines, 2) is None
    # Uniform chunks keep the vectorized fixed-width path.
    assert split_lines(b"ab\ncd\n").dtype.kind == "U"


def test_stdin_source_itemized_drains_burst(monkeypatch):
    """Itemized stdin reads raw fd chunks: a multi-line burst is fully
    emitted by the poll that saw it readable — nothing is stranded in
    a text-layer buffer behind a not-ready select()."""
    from bytewax_tpu.connectors.stdio import _StdInPartition

    r, w = os.pipe()
    try:
        stream = os.fdopen(r, "rb", buffering=0)
        part = _StdInPartition(False, 1 << 16, stream)
        os.write(w, b"a\nb\nc\n")
        assert part.next_batch() == ["a", "b", "c"]
        assert part.next_batch() == []  # quiet pipe: select not ready
        os.write(w, b"tail")
        os.close(w)
        assert part.next_batch() == []  # partial line carried
        assert part.next_batch() == ["tail"]  # EOF flush
        with pytest.raises(StopIteration):
            part.next_batch()
    finally:
        stream.close()
        try:
            os.close(w)
        except OSError:
            pass


def test_stdin_source_itemized_text_stream_fallback(monkeypatch):
    """A replaced sys.stdin with no fileno (StringIO) works in both
    modes — text reads are encoded before the line splitter."""
    import io

    from bytewax_tpu.connectors.stdio import StdInSource

    monkeypatch.setattr("sys.stdin", io.StringIO("one\ntwo\nthree"))
    out = []
    flow = Dataflow("stdin_item_fallback")
    s = op.input("inp", flow, StdInSource())
    op.output("out", s, TestingSink(out))
    run_main(flow)
    assert out == ["one", "two", "three"]


# -- bucketed padding: the recompile pin ------------------------------------


def test_bucketed_padding_bounds_compiles(monkeypatch):
    """Feed 100 random batch lengths through the device tier: compile
    count must stay bounded (every length pads onto the small bucket
    ladder — on the test's sharded 8-device mesh the exchange
    capacity adds a second, also pow-2-bucketed, compile key) and
    must CONVERGE: replaying the same lengths compiles nothing."""
    monkeypatch.setenv("BYTEWAX_TPU_INGEST_TARGET_ROWS", "0")
    lens = np.random.RandomState(7).randint(1, 1001, size=100)

    def feed(seed):
        rng = np.random.RandomState(seed)
        batches = [
            ColumnarBatch(
                {
                    "key": np.array(
                        [f"k{i % 8}" for i in range(n)]
                    ),
                    "value": rng.randint(0, 9, size=n).astype(
                        np.int64
                    ),
                }
            )
            for n in lens
        ]
        out = []
        run_main(
            _sum_flow("pad_pin", _ColumnarSource(batches), out),
            epoch_interval=ZERO_TD,
        )
        assert sorted(out) == _sum_oracle(batches)

    c0 = flight.RECORDER.counters.get("xla_compile_count", 0)
    feed(seed=1)
    churn = flight.RECORDER.counters.get("xla_compile_count", 0) - c0
    assert 0 < churn <= 30, (
        f"{churn} XLA compiles across 100 random batch lengths — "
        "bucketed padding must keep dispatch shapes on the ladder, "
        "not compile per shape"
    )
    # And the shape set converges: a second pass over the same
    # lengths re-traces at most the handful of per-run-instance
    # programs (the sharded step cache is per state instance), never
    # anything per-shape.
    c1 = flight.RECORDER.counters.get("xla_compile_count", 0)
    feed(seed=2)
    rerun = flight.RECORDER.counters.get("xla_compile_count", 0) - c1
    assert rerun <= min(churn, 8), (
        f"{rerun} XLA compiles on replaying identical batch lengths "
        f"(first pass: {churn}) — bucketed shapes are not converging"
    )


def test_pad_len_bucket_ladder(monkeypatch):
    assert batching.pad_len(1) == 32  # floor bucket (2**5)
    assert batching.pad_len(32) == 32
    assert batching.pad_len(33) == 64
    assert batching.pad_len(1000) == 1024
    assert batching.pad_len(4, floor_pow=2) == 4  # call-site floor
    # Above the cap: round up to a cap multiple, not the next power
    # of two (bounded over-allocation for giant batches).
    monkeypatch.setenv("BYTEWAX_TPU_PAD_MAX_POW", "10")
    batching.reconfigure()
    try:
        assert batching.pad_len(1500) == 2048
        assert batching.pad_len(5000) == 5120  # 5 * 1024, not 8192
    finally:
        monkeypatch.delenv("BYTEWAX_TPU_PAD_MAX_POW")
        batching.reconfigure()


# -- adaptive micro-batch coalescing ----------------------------------------


def test_flatten_annotates_accel_bound_inputs():
    """The lowering pass arms coalescing exactly for inputs routed to
    a non-session device-tier step."""

    def input_conf(flow):
        plan = flatten(flow)
        (inp,) = (o for o in plan.ops if o.name == "input")
        return inp.conf["_accel_bound"]

    out = []
    accel = _sum_flow("ab_accel", TestingSource([("a", 1)]), out)
    assert input_conf(accel) is True

    host = Dataflow("ab_host")
    s = op.input("inp", host, TestingSource([1]))
    op.output("out", op.map("x2", s, lambda x: x * 2), TestingSink(out))
    assert input_conf(host) is False

    # Session windows merge by arrival grouping: re-batching would
    # change their metadata, so they never arm coalescing.
    import bytewax_tpu.operators.windowing as w
    from bytewax_tpu.operators.windowing import EventClock, SessionWindower

    sess = Dataflow("ab_session")
    s = op.input("inp", sess, TestingSource([]))
    clock = EventClock(
        ts_getter=lambda item: item[0],
        wait_for_system_duration=ZERO_TD,
    )
    wo = w.count_window(
        "count",
        s,
        clock,
        SessionWindower(gap=timedelta(seconds=10)),
        key=lambda item: item[1],
    )
    op.output("out", wo.down, TestingSink(out))
    assert input_conf(sess) is False


def test_coalescing_merges_trickle_batches(monkeypatch):
    """A source trickling single rows is re-batched to the target at
    ingest — fewer, larger deliveries, same output."""
    monkeypatch.setenv("BYTEWAX_TPU_INGEST_TARGET_ROWS", "64")
    inp = [(f"k{i % 5}", i) for i in range(400)]
    c0 = flight.RECORDER.counters.get("ingest_coalesced_polls", 0)
    out = []
    run_main(
        _sum_flow("coalesce_eq", TestingSource(inp, batch_size=1), out),
        epoch_interval=ZERO_TD,
    )
    oracle = {}
    for k, v in inp:
        oracle[k] = oracle.get(k, 0) + v
    assert sorted(out) == sorted(oracle.items())
    assert (
        flight.RECORDER.counters.get("ingest_coalesced_polls", 0) - c0
        > 300
    )


def test_coalescing_defers_abort_until_rows_flow(
    recovery_config, monkeypatch
):
    """An abort hit while coalescing re-raises only at the NEXT poll:
    the rows accumulated before it are delivered, snapshotted, and
    never replayed — exactly-once matches the uncoalesced engine."""
    monkeypatch.setenv("BYTEWAX_TPU_INGEST_TARGET_ROWS", "64")
    items = list(range(20))
    tail = list(range(20, 30))
    inp = items + [TestingSource.ABORT()] + tail

    def flow():
        f = Dataflow("coalesce_abort")
        s = op.input("inp", f, TestingSource(inp, batch_size=1))
        out = []
        op.output("out", s, TestingSink(out))
        return f, out

    f1, out1 = flow()
    run_main(f1, epoch_interval=ZERO_TD, recovery_config=recovery_config)
    assert out1 == items  # everything gathered before the abort flowed
    f2, out2 = flow()
    run_main(f2, epoch_interval=ZERO_TD, recovery_config=recovery_config)
    assert out1 + out2 == items + tail


def test_coalesce_target_defaults(monkeypatch):
    monkeypatch.delenv("BYTEWAX_TPU_INGEST_TARGET_ROWS", raising=False)
    monkeypatch.delenv("BYTEWAX_TPU_STATE_BUDGET", raising=False)
    assert batching.coalesce_target(True) > 0
    assert batching.coalesce_target(False) == 0
    monkeypatch.setenv("BYTEWAX_TPU_INGEST_TARGET_ROWS", "128")
    assert batching.coalesce_target(False) == 128
    monkeypatch.setenv("BYTEWAX_TPU_INGEST_TARGET_ROWS", "0")
    assert batching.coalesce_target(True) == 0
    # Budgeted residency sizes deliveries against the key budget, so
    # it keeps source granularity unless a target is forced.
    monkeypatch.delenv("BYTEWAX_TPU_INGEST_TARGET_ROWS", raising=False)
    monkeypatch.setenv("BYTEWAX_TPU_STATE_BUDGET", "4")
    assert batching.coalesce_target(True) == 0


def test_merge_batches_rules():
    a = ColumnarBatch({"key": np.array(["a"]), "value": np.array([1.0])})
    b = ColumnarBatch({"key": np.array(["b"]), "value": np.array([2.0])})
    assert batching.can_merge(a, b)
    merged = batching.merge_batches([a, b])
    assert merged.cols["key"].tolist() == ["a", "b"]
    assert merged.cols["value"].tolist() == [1.0, 2.0]
    assert batching.can_merge([1], [2])
    assert not batching.can_merge([1], a)
    c = ColumnarBatch({"line": np.array(["x"])})
    assert not batching.can_merge(a, c)  # different columns


# -- source-lag accounting on the columnar path -----------------------------


def test_columnar_batch_event_lag():
    from bytewax_tpu.engine.driver import _batch_event_lag_s

    now = datetime(2026, 1, 1, 0, 0, 10, tzinfo=timezone.utc)
    dt_col = np.array(
        ["2026-01-01T00:00:00", "2026-01-01T00:00:07"],
        dtype="datetime64[us]",
    )
    lag = _batch_event_lag_s(
        ColumnarBatch({"key": np.array(["a", "b"]), "ts": dt_col}), now
    )
    assert lag == pytest.approx(3.0)
    # Numeric ts columns are microseconds since epoch (the convention
    # the batch-native Kafka connector emits).
    us_col = (
        dt_col.astype("int64")
        - np.datetime64("1970-01-01", "us").astype("int64")
    )
    lag = _batch_event_lag_s(
        ColumnarBatch({"key": np.array(["a", "b"]), "ts": us_col}), now
    )
    assert lag == pytest.approx(3.0)
    # No ts column / NaT: no discoverable event time.
    assert (
        _batch_event_lag_s(
            ColumnarBatch({"value": np.array([1.0])}), now
        )
        is None
    )
    assert (
        _batch_event_lag_s(
            ColumnarBatch(
                {"ts": np.array(["NaT"], dtype="datetime64[us]")}
            ),
            now,
        )
        is None
    )


# -- the other batch-native connectors --------------------------------------


def test_stdin_source_columnar(monkeypatch):
    """Chunked stdin decode: raw chunks in, line batches out, final
    unterminated line flushed at EOF."""
    import io

    from bytewax_tpu.connectors.stdio import StdInSource

    data = b"alpha\nbeta\ngamma"
    fake = type("FakeStdin", (), {"buffer": io.BytesIO(data)})()
    monkeypatch.setattr("sys.stdin", fake)
    out = []
    flow = Dataflow("stdin_col")
    s = op.input("inp", flow, StdInSource(columnar=True, chunk_bytes=4))
    op.output("out", s, TestingSink(out))
    run_main(flow)
    assert out == ["alpha", "beta", "gamma"]


def test_demo_source_batch_native_resume():
    """The batch-native random walk emits key/ts/value columns and its
    snapshot restarts the walk mid-stream without repeating or
    skipping steps."""
    from bytewax_tpu.connectors.demo import RandomMetricSource
    from bytewax_tpu.testing import poll_next_batch

    src = RandomMetricSource(
        "cpu", interval=ZERO_TD, count=10, seed=42, batch_size=4
    )
    part = src.build_part("demo", "cpu", None)
    first = poll_next_batch(part)
    assert sorted(first.cols) == ["key", "ts", "value"]
    assert first.cols["key"].tolist() == ["cpu"] * 4
    snap = part.snapshot()

    rest = []
    resumed = src.build_part("demo", "cpu", snap)
    while True:
        try:
            rest.extend(poll_next_batch(resumed).cols["value"].tolist())
        except StopIteration:
            break
    straight = src.build_part("demo", "cpu", None)
    walk = []
    while True:
        try:
            walk.extend(poll_next_batch(straight).cols["value"].tolist())
        except StopIteration:
            break
    assert first.cols["value"].tolist() + rest == pytest.approx(walk)
    assert len(walk) == 10
