"""Flow-map tests (tentpole of the flow-map observability PR): the
per-step / per-edge live telemetry accumulator, the annotated
``GET /graph`` topology, the pure bottleneck attribution, and its
step-scoped feed into the rescale hint.

The flow map is always-on observability data on a global accumulator
(like the epoch ledger), so tests that assert per-run records reset
the module singleton first — never the engine's own state.
"""

import json
import os
import subprocess
import sys
import time
import urllib.request
from datetime import timedelta

import bytewax_tpu.operators as op
from bytewax_tpu.dataflow import Dataflow
from bytewax_tpu.engine import flowmap
from bytewax_tpu.engine.flowmap import (
    FlowMap,
    derive_bottleneck,
    device_footprint,
    payload_size,
    topology,
)
from bytewax_tpu.testing import TestingSink, TestingSource

ZERO_TD = timedelta(seconds=0)


def _reset_flowmap():
    fm = flowmap.FLOWMAP
    fm._rows.clear()
    fm._batches.clear()
    fm._edges.clear()
    fm._wire.clear()
    fm._device.clear()
    fm._lag.clear()
    fm.last = None
    fm._sealed.clear()
    fm._epoch_t0 = time.monotonic()


# -- derive_bottleneck: pure attribution -------------------------------


def test_bottleneck_queue_pressure_names_slowest_upstream():
    # Pressure at the sink's queue, but the slow sustained consumer
    # is the mapper feeding it: the walk goes transitively upstream
    # and names the busiest step on the path.
    steps = {
        "df.inp": {"busy_s": 0.2},
        "df.work": {"busy_s": 3.0},
        "df.out": {"busy_s": 0.1, "queue_depth": 5},
    }
    edges = [("df.inp", "df.work"), ("df.work", "df.out")]
    got = derive_bottleneck(steps, edges)
    assert got is not None
    step, why = got
    assert step == "df.work"
    assert "queue depth 5 at df.out" in why
    assert "slowest upstream df.work" in why


def test_bottleneck_lag_pressure_wins_over_smaller_queue():
    # The LARGEST pressure signal anchors the walk: a 30s watermark
    # lag outranks a depth-2 queue elsewhere.
    steps = {
        "df.a": {"busy_s": 1.0, "queue_depth": 2},
        "df.b": {"busy_s": 0.5, "lag_s": 30.0},
    }
    got = derive_bottleneck(steps, edges=[])
    assert got is not None
    step, why = got
    assert step == "df.b"
    assert "lag 30.0s at df.b" in why


def test_bottleneck_pressure_with_no_busy_upstream_names_site():
    # No attributed busy time anywhere on the pressured path: the
    # pressure site itself is the answer (never a zero-busy winner).
    steps = {"df.x": {"queue_depth": 4}, "df.up": {}}
    got = derive_bottleneck(steps, edges=[("df.up", "df.x")])
    assert got is not None and got[0] == "df.x"


def test_bottleneck_dominant_share_without_pressure():
    steps = {
        "df.inp": {"busy_s": 0.1},
        "df.slow": {"busy_s": 2.0},
        "df.out": {"busy_s": 0.1},
    }
    got = derive_bottleneck(steps)
    assert got is not None
    step, why = got
    assert step == "df.slow"
    assert "of attributed busy time" in why


def test_bottleneck_none_when_nothing_qualifies():
    # Balanced load, no pressure: naming a "bottleneck" would be
    # noise — the attribution must decline.
    assert derive_bottleneck({}) is None
    assert (
        derive_bottleneck(
            {"df.a": {"busy_s": 1.0}, "df.b": {"busy_s": 1.0}}
        )
        is None
    )
    assert derive_bottleneck({"df.a": {}, "df.b": {}}) is None


def test_bottleneck_deterministic_tie_break():
    # Equal-pressure ties resolve on step id, so repeated polls never
    # flap between two names.
    steps = {
        "df.b": {"queue_depth": 3, "busy_s": 1.0},
        "df.a": {"queue_depth": 3, "busy_s": 1.0},
    }
    got1 = derive_bottleneck(steps)
    got2 = derive_bottleneck(dict(reversed(list(steps.items()))))
    assert got1 == got2


# -- the FlowMap accumulator -------------------------------------------


def test_flowmap_seal_record_shape_and_reset():
    fm = FlowMap()
    fm.add_rows("df.inp", "out", 100)
    fm.add_rows("df.work", "in", 100)
    fm.add_rows("df.work", "in", 60)
    fm.add_rows("df.work", "out", 160)
    fm.add_edge("df.inp.down", 100)
    fm.add_wire(1, "df.work.up", 50, 4096)
    fm.set_device("df.win", 7, 1 << 20)
    fm.set_lag("df.win", 2.5)
    rec = fm.seal(3, queue_depth={"df.win": 2})

    assert rec["epoch"] == 3 and rec["wall_s"] > 0
    work = rec["steps"]["df.work"]
    assert work["rows_in"] == 160 and work["batches_in"] == 2
    assert work["batch_rows_in"] == 80.0
    assert work["rows_out"] == 160
    assert work["rate_in_per_s"] > 0
    win = rec["steps"]["df.win"]
    assert win["device_keys"] == 7
    assert win["device_bytes"] == 1 << 20
    assert win["watermark_lag_s"] == 2.5
    assert win["queue_depth_at_drain"] == 2
    assert rec["edges"]["df.inp.down"]["rows"] == 100
    assert rec["wire"]["1"]["df.work.up"] == {
        "frames": 1,
        "rows": 50,
        "bytes": 4096,
    }
    # Sealed record is the published summary; accumulators reset.
    assert fm.summary() is rec
    assert fm.recent() == [rec]
    empty = fm.seal(4)
    assert empty["steps"] == {} and empty["edges"] == {}


def test_flowmap_prometheus_mirror():
    from prometheus_client import REGISTRY

    fm = FlowMap()
    fm.add_rows("pm_df.step", "in", 40)
    fm.set_lag("pm_df.step", 1.25)
    fm.set_device("pm_df.step", 3, 2048)
    fm.seal(1)
    assert (
        REGISTRY.get_sample_value(
            "bytewax_step_rows_count_total",
            {"step_id": "pm_df.step", "direction": "in"},
        )
        >= 40
    )
    assert (
        REGISTRY.get_sample_value(
            "bytewax_step_watermark_lag_seconds",
            {"step_id": "pm_df.step"},
        )
        == 1.25
    )
    assert (
        REGISTRY.get_sample_value(
            "bytewax_step_device_bytes", {"step_id": "pm_df.step"}
        )
        == 2048
    )


def test_payload_size_and_device_footprint_units():
    import numpy as np

    from bytewax_tpu.engine.arrays import ArrayBatch

    batch = ArrayBatch(
        {
            "key_id": np.zeros(10, dtype=np.int32),
            "v": np.ones(10, dtype=np.float64),
        },
        key_vocab=np.array(["k"]),
    )
    rows, nbytes = payload_size(batch)
    assert rows == 10
    assert nbytes == 10 * 4 + 10 * 8
    # Itemized payloads report rows only.
    assert payload_size([("k", 1), ("k", 2)]) == (2, 0)

    class _Slots:
        key_to_slot = {"a": 0, "b": 1}
        _fields = {"acc": np.zeros((4, 2), dtype=np.float32)}

    keys, nbytes = device_footprint(_Slots())
    assert keys == 2 and nbytes == 32
    # A wrapper delegating to the same tables never double-counts.
    inner = _Slots()

    class _Wrap:
        def __init__(self):
            self._inner = inner
            self.key_to_slot = inner.key_to_slot
            self._fields = inner._fields

    assert device_footprint(_Wrap()) == (2, 32)


# -- topology over the lowered plan ------------------------------------


def test_topology_steps_edges_and_tiers(monkeypatch):
    monkeypatch.setenv("BYTEWAX_TPU_ACCEL", "1")
    from bytewax_tpu.engine.flatten import flatten

    from bytewax_tpu import xla

    flow = Dataflow("topo_df")
    s = op.input("inp", flow, TestingSource([("k", 1.0)]))
    st = xla.stats_final("sum", s)
    fmt = op.map_value("fmt", st, str)
    op.output("out", fmt, TestingSink([]))
    topo = topology(flatten(flow))

    by_id = {n["step_id"]: n for n in topo["steps"]}
    # One node per lowered core op, with its static tier.
    assert any("inp" in sid for sid in by_id)
    accel_tiers = {
        n["step_id"]: n["tier"]
        for n in topo["steps"]
        if n["tier"] == "device"
    }
    assert accel_tiers, by_id  # the annotated aggregation is device
    # Every edge names a consumer that exists; sources resolve.
    for e in topo["edges"]:
        assert e["dst"] in by_id
        assert e["src"] is None or e["src"] in by_id
        assert isinstance(e["port"], str)
    # The lowered graph is connected input->output.
    dsts = {e["dst"] for e in topo["edges"]}
    assert any("out" in d for d in dsts)


# -- GET /graph (in-process) -------------------------------------------


def test_graph_endpoint(entry_point, monkeypatch, tmp_path):
    # GET /graph returns the annotated topology under all 3 entry
    # points: steps with tiers, edges with ports, per-process
    # telemetry from the sealed flow-map records.
    monkeypatch.setenv("BYTEWAX_DATAFLOW_API_ENABLED", "1")
    monkeypatch.setenv("BYTEWAX_DATAFLOW_API_PORT", "13054")
    monkeypatch.chdir(tmp_path)
    _reset_flowmap()

    captured = {}

    class _ProbePartition:
        def __init__(self):
            self._seen = 0

        def write_batch(self, items):
            self._seen += 1
            # Poll late enough that at least one epoch has sealed a
            # flow-map record (the summary rides one close behind).
            if self._seen >= 3 and "graph" not in captured:
                with urllib.request.urlopen(
                    "http://127.0.0.1:13054/graph", timeout=5
                ) as resp:
                    captured["graph"] = json.loads(resp.read())

        def close(self):
            pass

    from bytewax_tpu.outputs import DynamicSink

    class _ProbeSink(DynamicSink):
        def build(self, step_id, worker_index, worker_count):
            return _ProbePartition()

    flow = Dataflow("graph_df")
    s = op.input(
        "inp", flow, TestingSource(list(range(40)), batch_size=4)
    )
    s = op.map("double", s, lambda x: x * 2)
    op.output("out", s, _ProbeSink())
    entry_point(flow, epoch_interval=ZERO_TD)

    graph = captured["graph"]
    assert graph["flow_id"] == "graph_df"
    assert graph["proc_id"] == 0 and graph["proc_count"] == 1
    by_id = {n["step_id"]: n for n in graph["steps"]}
    mapper = next(sid for sid in by_id if ".double." in sid)
    assert by_id[mapper]["tier"] == "host"
    # The mapper's sealed telemetry shows rows flowing through it.
    tele = by_id[mapper]["telemetry"]
    assert "0" in tele, graph
    assert tele["0"]["rows_in"] > 0 and tele["0"]["rows_out"] > 0
    assert tele["0"]["rate_in_per_s"] > 0
    # Edges carry per-process routed-row telemetry too.
    assert any(
        e["telemetry"].get("0", {}).get("rows", 0) > 0
        for e in graph["edges"]
    ), graph["edges"]
    # And the document is valid JSON end to end (it arrived as such).
    assert isinstance(graph["wire"], dict)
    assert "bottleneck" in graph


# -- the acceptance check: a throttled step is named -------------------


def test_throttled_step_named_bottleneck(entry_point, monkeypatch, tmp_path):
    # Throttle ONE host-tier mapper: derive_bottleneck must name
    # exactly that step, /graph carries it, and /status's
    # rescale_hint reasons carry the step-scoped attribution — under
    # all 3 entry points.
    monkeypatch.setenv("BYTEWAX_DATAFLOW_API_ENABLED", "1")
    monkeypatch.setenv("BYTEWAX_DATAFLOW_API_PORT", "13055")
    monkeypatch.chdir(tmp_path)
    _reset_flowmap()
    from bytewax_tpu.engine import flight

    flight.RECORDER.last_ledger = None

    captured = {}

    class _ProbePartition:
        def __init__(self):
            self._seen = 0

        def write_batch(self, items):
            self._seen += 1
            if self._seen >= 4 and "status" not in captured:
                with urllib.request.urlopen(
                    "http://127.0.0.1:13055/graph", timeout=5
                ) as resp:
                    graph = json.loads(resp.read())
                if graph.get("bottleneck") is None:
                    return  # not sealed yet; retry next batch
                captured["graph"] = graph
                with urllib.request.urlopen(
                    "http://127.0.0.1:13055/status", timeout=5
                ) as resp:
                    captured["status"] = json.loads(resp.read())

        def close(self):
            pass

    from bytewax_tpu.outputs import DynamicSink

    class _ProbeSink(DynamicSink):
        def build(self, step_id, worker_index, worker_count):
            return _ProbePartition()

    flow = Dataflow("bn_df")
    s = op.input(
        "inp", flow, TestingSource(list(range(40)), batch_size=4)
    )
    s = op.map("fast", s, lambda x: x)
    s = op.map("slow", s, lambda x: (time.sleep(0.004), x)[1])
    op.output("out", s, _ProbeSink())
    entry_point(flow, epoch_interval=ZERO_TD)

    assert "status" in captured, "bottleneck never derived in-run"
    bn = captured["graph"]["bottleneck"]
    assert ".slow." in bn["step"], bn
    assert ".fast." not in bn["step"]
    assert "busy time" in bn["why"] or "at " in bn["why"]
    # The rescale hint carries the SAME attribution as a step-scoped
    # reason (an attribution, never itself a grow trigger).
    hint = captured["status"]["rescale_hint"]
    assert any(
        "bottleneck step" in r and ".slow." in r
        for r in hint["reasons"]
    ), hint["reasons"]
    assert hint["signals"]["bottleneck"]["step"] == bn["step"]


# -- the acceptance check: 2-process cluster /graph merge --------------


def test_graph_cluster_merges_both_processes(tmp_path):
    # In a real 2-process cluster, any process's /graph returns ONE
    # topology with BOTH processes' per-step rates merged in via the
    # existing epoch-close gsync telemetry summary — no new frame
    # kinds (the analyzer inventory tests pin that side).
    flow_py = tmp_path / "graph_flow.py"
    flow_py.write_text(
        """
import time
import bytewax_tpu.operators as op
from bytewax_tpu.dataflow import Dataflow
from bytewax_tpu.inputs import DynamicSource, StatelessSourcePartition
from bytewax_tpu.outputs import DynamicSink, StatelessSinkPartition


class _Tick(StatelessSourcePartition):
    def __init__(self, worker_index):
        self._i = 0
        self._w = worker_index

    def next_batch(self):
        if self._i >= 40:
            raise StopIteration()
        self._i += 1
        time.sleep(0.1)
        return [(f"k{self._w}", 1), (f"k{self._i % 3}", 1)]


class TickSource(DynamicSource):
    def build(self, step_id, worker_index, worker_count):
        return _Tick(worker_index)


class _Null(StatelessSinkPartition):
    def write_batch(self, items):
        pass


class NullSink(DynamicSink):
    def build(self, step_id, worker_index, worker_count):
        return _Null()


flow = Dataflow("graph_cluster_df")
s = op.input("inp", flow, TickSource())
s = op.stateful_map("sum", s, lambda st, v: ((st or 0) + v, (st or 0) + v))
op.output("out", s, NullSink())
"""
    )
    import socket

    ports = []
    for _ in range(2):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        ports.append(s.getsockname()[1])
        s.close()

    env = dict(os.environ)
    env["PYTHONPATH"] = "/root/repo" + os.pathsep + env.get(
        "PYTHONPATH", ""
    )
    env["BYTEWAX_TPU_PLATFORM"] = "cpu"
    env["BYTEWAX_TPU_ACCEL"] = "0"
    env["BYTEWAX_DATAFLOW_API_ENABLED"] = "1"
    env["BYTEWAX_DATAFLOW_API_PORT"] = "13056"
    env["BYTEWAX_ADDRESSES"] = ";".join(
        f"127.0.0.1:{p}" for p in ports
    )
    env["BYTEWAX_TPU_DIAL_TIMEOUT_S"] = "120"
    procs = []
    for proc_id in range(2):
        penv = dict(env)
        penv["BYTEWAX_PROCESS_ID"] = str(proc_id)
        procs.append(
            subprocess.Popen(
                [
                    sys.executable,
                    "-m",
                    "bytewax_tpu.run",
                    f"{flow_py}:flow",
                    "-s",
                    "0.3",
                ],
                env=penv,
                cwd=tmp_path,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
            )
        )
    graph = None
    try:
        deadline = time.monotonic() + 150
        while time.monotonic() < deadline:
            try:
                with urllib.request.urlopen(
                    "http://127.0.0.1:13056/graph", timeout=2
                ) as resp:
                    got = json.loads(resp.read())
            except OSError:
                time.sleep(0.2)
                continue
            # Wait until the stateful step's telemetry carries BOTH
            # processes (this proc's record is live; the peer's rides
            # the epoch-close summary, one close behind).
            nodes = {
                n["step_id"]: n for n in got.get("steps", [])
            }
            merged = [
                n
                for n in nodes.values()
                if {"0", "1"} <= set(n.get("telemetry", {}))
            ]
            if merged:
                graph = got
                break
            time.sleep(0.2)
    finally:
        errs = []
        for proc in procs:
            try:
                _out, err = proc.communicate(timeout=120)
            except subprocess.TimeoutExpired:
                proc.kill()
                _out, err = proc.communicate()
            errs.append(err)
    for proc, err in zip(procs, errs):
        assert proc.returncode == 0, err[-2000:].decode(errors="replace")
    assert graph is not None, "peer flow-map never reached proc 0"
    # ONE topology (the plan is identical cluster-wide)...
    assert graph["flow_id"] == "graph_cluster_df"
    assert graph["proc_count"] == 2
    step_ids = [n["step_id"] for n in graph["steps"]]
    assert len(step_ids) == len(set(step_ids))
    # ...with both processes' rates on the shared steps.
    merged = [
        n
        for n in graph["steps"]
        if {"0", "1"} <= set(n["telemetry"])
    ]
    assert merged
    for node in merged:
        for pid in ("0", "1"):
            tele = node["telemetry"][pid]
            assert tele.get("rows_in", 0) >= 0
            assert "rate_in_per_s" in tele or "rate_out_per_s" in tele
    # The keyed exchange crossed the mesh: per-peer wire telemetry
    # shows shipped rows from at least one process's record.
    wire = graph["wire"]
    assert any(
        streams
        for per_proc in wire.values()
        for streams in per_proc.values()
    ), wire
