"""Mesh-sharded keyed aggregation: the all_to_all exchange step, the
ShardedAggState engine tier, dataflow equivalence with the host tier,
and cross-tier recovery (host <-> single-device <-> mesh)."""

import collections

import numpy as np
import pytest

import bytewax_tpu.operators as op
from bytewax_tpu import xla
from bytewax_tpu.dataflow import Dataflow
from bytewax_tpu.engine.arrays import ArrayBatch
from bytewax_tpu.testing import TestingSink, TestingSource, run_main
from tests.test_xla import ArraySource


def _mesh(n=8):
    import jax

    from bytewax_tpu.parallel.mesh import make_mesh

    if len(jax.devices()) < n:
        pytest.skip(f"needs {n} virtual devices")
    return make_mesh(n)


# -- make_sharded_step directly ---------------------------------------------


def _run_step(mesh, kind, key_ids, values, cap_per_shard=64, capacity=None,
              dtype=None):
    import jax
    import jax.numpy as jnp

    from bytewax_tpu.ops.sharded import init_sharded_fields, make_sharded_step
    from bytewax_tpu.parallel.mesh import key_sharding

    n_shards = len(mesh.devices)
    if dtype is None:
        dtype = jnp.float32
    if capacity is None:
        # true per-(source block, dest) maximum
        rows_per_shard = len(key_ids) // n_shards
        block_of = np.arange(len(key_ids)) // rows_per_shard
        dest = key_ids % n_shards
        capacity = int(
            np.bincount(
                block_of * n_shards + dest, minlength=n_shards * n_shards
            ).max()
        )
    fields = init_sharded_fields(
        xla_kind(kind), mesh, cap_per_shard, dtype=dtype
    )
    step = make_sharded_step(mesh, kind, cap_per_shard, capacity, dtype=dtype)
    sh = key_sharding(mesh)
    out = step(
        fields,
        jax.device_put(jnp.asarray(key_ids), sh),
        jax.device_put(jnp.asarray(values), sh),
        jax.device_put(jnp.ones(len(key_ids), dtype=bool), sh),
    )
    return {k: np.asarray(v) for k, v in out.items()}


def xla_kind(name):
    from bytewax_tpu.ops.segment import AGG_KINDS

    return AGG_KINDS[name]


def _oracle_index(kid, n_shards, cap_per_shard):
    shard, slot = kid % n_shards, kid // n_shards
    return shard * cap_per_shard + slot


def test_sharded_step_matches_oracle_random():
    mesh = _mesh()
    rng = np.random.RandomState(1)
    n, n_keys, cap = 512, 100, 64
    key_ids = rng.randint(0, n_keys, size=n).astype(np.int32)
    values = rng.randn(n).astype(np.float32)
    out = _run_step(mesh, "stats", key_ids, values, cap_per_shard=cap)
    for k in range(n_keys):
        idx = _oracle_index(k, 8, cap)
        rows = values[key_ids == k]
        assert out["count"][idx] == len(rows)
        if len(rows):
            np.testing.assert_allclose(out["sum"][idx], rows.sum(), rtol=1e-5)
            np.testing.assert_allclose(out["min"][idx], rows.min(), rtol=1e-6)
            np.testing.assert_allclose(out["max"][idx], rows.max(), rtol=1e-6)
    assert out["count"].sum() == n  # row conservation


def test_sharded_step_nonuniform_distribution():
    # All rows target two shards; every other bucket is empty.
    mesh = _mesh()
    n, cap = 256, 64
    key_ids = np.where(
        np.arange(n) % 2 == 0, 0, 1
    ).astype(np.int32)  # keys 0 (shard 0) and 1 (shard 1)
    values = np.ones(n, dtype=np.float32)
    out = _run_step(mesh, "sum", key_ids, values, cap_per_shard=cap)
    assert out["sum"][_oracle_index(0, 8, cap)] == n // 2
    assert out["sum"][_oracle_index(1, 8, cap)] == n // 2
    assert out["sum"].sum() == n


def test_sharded_step_float_bitcast_roundtrip():
    # Negative / subnormal-ish floats must survive the int32 bitcast
    # ride through the exchange exactly.
    mesh = _mesh()
    cap = 16
    # Smallest NORMAL float32 included; subnormals are out of scope
    # (XLA flushes them to zero on every tier).
    specials = np.array(
        [-0.0, 1.5, -2.25, 1.2e-38, -1e38, 3.14159], dtype=np.float32
    )
    n = 64
    key_ids = (np.arange(n) % len(specials)).astype(np.int32)
    values = specials[key_ids]
    out = _run_step(mesh, "max", key_ids, values, cap_per_shard=cap)
    for k, v in enumerate(specials):
        idx = _oracle_index(k, 8, cap)
        assert out["max"][idx] == np.float32(v), (k, v, out["max"][idx])


def test_sharded_step_int32_exact():
    import jax.numpy as jnp

    mesh = _mesh()
    cap = 16
    n = 64
    key_ids = np.zeros(n, dtype=np.int32)
    values = np.full(n, 2**24 + 1, dtype=np.int32)  # not f32-representable
    out = _run_step(
        mesh, "sum", key_ids, values, cap_per_shard=cap, dtype=jnp.int32
    )
    assert out["sum"][_oracle_index(0, 8, cap)] == n * (2**24 + 1)


def test_sharded_step_capacity_boundary():
    # Exactly capacity rows from one source block to one destination:
    # nothing may be lost at the boundary.
    mesh = _mesh()
    cap_per_shard, capacity = 16, 8
    n = 64  # 8 rows per source block
    key_ids = np.zeros(n, dtype=np.int32)  # all to shard 0, count==capacity
    values = np.ones(n, dtype=np.float32)
    out = _run_step(
        mesh, "sum", key_ids, values,
        cap_per_shard=cap_per_shard, capacity=capacity,
    )
    assert out["sum"][_oracle_index(0, 8, cap_per_shard)] == n


# -- ShardedAggState --------------------------------------------------------


def test_sharded_state_matches_single_device():
    from bytewax_tpu.engine.sharded_state import ShardedAggState
    from bytewax_tpu.engine.xla import DeviceAggState

    mesh = _mesh()
    rng = np.random.RandomState(2)
    n = 3000
    keys = np.array([f"k{i:03d}" for i in rng.randint(0, 413, size=n)])
    vals = (rng.randn(n) * 10).round(1).astype(np.float64)

    sharded = ShardedAggState("stats", mesh)
    single = DeviceAggState("stats")
    for i in range(0, n, 700):  # uneven batches
        sharded.update(keys[i : i + 700], vals[i : i + 700])
        single.update(keys[i : i + 700], vals[i : i + 700])
    a, b = sharded.finalize(), single.finalize()
    assert [k for k, _ in a] == [k for k, _ in b]
    for (ka, va), (_kb, vb) in zip(a, b):
        np.testing.assert_allclose(va, vb, rtol=1e-5, err_msg=ka)


def test_sharded_state_skewed_hot_key():
    # One key receives far more rows than any per-bucket guess would
    # allow; the host-sized exchange must not lose a single row.
    from bytewax_tpu.engine.sharded_state import ShardedAggState

    mesh = _mesh()
    st = ShardedAggState("count", mesh)
    keys = np.array(["hot"] * 9000 + [f"cold{i}" for i in range(100)])
    st.update(keys, np.zeros(len(keys)))
    out = dict(st.finalize())
    assert out["hot"] == 9000
    assert sum(out.values()) == 9100


def test_sharded_state_dict_encoded_batches():
    from bytewax_tpu.engine.sharded_state import ShardedAggState

    mesh = _mesh()
    st = ShardedAggState("stats", mesh)
    vocab = np.array([f"station{i}" for i in range(50)])
    rng = np.random.RandomState(3)
    rows = []
    for _ in range(4):
        ids = rng.randint(0, 50, size=500).astype(np.int32)
        temps = rng.randint(-400, 400, size=500).astype(np.int16)
        rows.append((ids, temps))
        st.update_batch(
            ArrayBatch(
                {"key_id": ids, "value": temps},
                key_vocab=vocab,
                value_scale=0.1,
            )
        )
    out = dict(st.finalize())
    groups = collections.defaultdict(list)
    for ids, temps in rows:
        for i, t in zip(ids.tolist(), temps.tolist()):
            groups[f"station{i}"].append(t * 0.1)
    assert set(out) == set(groups)
    for k, g in groups.items():
        mn, mean, mx, cnt = out[k]
        assert cnt == len(g)
        np.testing.assert_allclose(mn, min(g), atol=1e-4)
        np.testing.assert_allclose(mx, max(g), atol=1e-4)
        np.testing.assert_allclose(mean, sum(g) / len(g), atol=1e-3)


def test_sharded_state_growth_keeps_state():
    # Keys folded before a capacity growth must keep their state after.
    from bytewax_tpu.engine.sharded_state import ShardedAggState

    mesh = _mesh()
    st = ShardedAggState("sum", mesh, cap_per_shard=8)
    st.update(np.array(["early"]), np.array([5.0]))
    many = np.array([f"key{i:05d}" for i in range(1000)])
    st.update(many, np.ones(1000))
    st.update(np.array(["early"]), np.array([7.0]))
    out = dict(st.finalize())
    assert out["early"] == 12.0
    assert len(out) == 1001


# -- engine integration -----------------------------------------------------


def _brc_flow(batches, out):
    flow = Dataflow("sharded_df")
    s = op.input("inp", flow, ArraySource(batches))
    r = xla.stats_final("stats", s)
    op.output("out", r, TestingSink(out))
    return flow


def _brc_batches(n=4000, n_keys=200, seed=4):
    rng = np.random.RandomState(seed)
    batches = []
    for i in range(0, n, 512):
        m = min(512, n - i)
        batches.append(
            ArrayBatch(
                {
                    "key": np.array(
                        [f"s{k:03d}" for k in rng.randint(0, n_keys, size=m)]
                    ),
                    "value": (rng.randn(m) * 10).round(1),
                }
            )
        )
    return batches


def test_dataflow_sharded_matches_host_tier(monkeypatch):
    # The "Done" bar from the round-1 verdict: a dataflow on the
    # 8-device mesh produces output identical to the host tier.
    batches = _brc_batches()

    monkeypatch.setenv("BYTEWAX_TPU_ACCEL", "1")
    monkeypatch.setenv("BYTEWAX_TPU_SHARD", "8")
    sharded = []
    run_main(_brc_flow(batches, sharded))

    monkeypatch.setenv("BYTEWAX_TPU_SHARD", "0")
    single = []
    run_main(_brc_flow(batches, single))

    monkeypatch.setenv("BYTEWAX_TPU_ACCEL", "0")
    host = []
    run_main(_brc_flow(batches, host))

    assert [k for k, _ in sharded] == [k for k, _ in host]
    for (k, vs), (_k1, v1), (_k2, vh) in zip(sharded, single, host):
        np.testing.assert_allclose(vs, v1, rtol=1e-5, err_msg=k)
        np.testing.assert_allclose(vs, vh, rtol=1e-4, err_msg=k)


def test_dataflow_sharded_reduce_sum_exact(monkeypatch):
    # Integer reduce via the mesh stays exact and byte-identical to
    # the host tier.
    monkeypatch.setenv("BYTEWAX_TPU_ACCEL", "1")
    monkeypatch.setenv("BYTEWAX_TPU_SHARD", "8")
    inp = [(f"k{i % 40}", i) for i in range(2000)]

    def build(out):
        flow = Dataflow("sum_df")
        s = op.input("inp", flow, TestingSource(inp, batch_size=128))
        r = op.reduce_final("sum", s, xla.SUM)
        op.output("out", r, TestingSink(out))
        return flow

    sharded = []
    run_main(build(sharded))
    monkeypatch.setenv("BYTEWAX_TPU_ACCEL", "0")
    host = []
    run_main(build(host))
    assert sharded == host


def test_sharded_cross_tier_recovery(tmp_path, monkeypatch):
    # Crash on the host tier, resume on the mesh; crash on the mesh,
    # resume on the host tier.  Snapshots are the same format.
    from bytewax_tpu.recovery import RecoveryConfig, init_db_dir
    from datetime import timedelta

    def build(inp, out):
        flow = Dataflow("rec_df")
        s = op.input("inp", flow, TestingSource(inp))
        r = op.reduce_final("sum", s, xla.SUM)
        op.output("out", r, TestingSink(out))
        return flow

    # host -> mesh
    d1 = tmp_path / "a"
    d1.mkdir()
    init_db_dir(d1, 1)
    rc1 = RecoveryConfig(str(d1))
    inp1 = [("k", 1.0), ("k", 2.0), TestingSource.ABORT(), ("k", 4.0)]
    out1: list = []
    monkeypatch.setenv("BYTEWAX_TPU_ACCEL", "0")
    run_main(build(inp1, out1), epoch_interval=timedelta(0), recovery_config=rc1)
    assert out1 == []
    monkeypatch.setenv("BYTEWAX_TPU_ACCEL", "1")
    monkeypatch.setenv("BYTEWAX_TPU_SHARD", "8")
    run_main(build(inp1, out1), epoch_interval=timedelta(0), recovery_config=rc1)
    assert out1 == [("k", 7.0)]

    # mesh -> host
    d2 = tmp_path / "b"
    d2.mkdir()
    init_db_dir(d2, 1)
    rc2 = RecoveryConfig(str(d2))
    inp2 = [("k", 1.0), ("k", 2.0), TestingSource.ABORT(), ("k", 4.0)]
    out2: list = []
    run_main(build(inp2, out2), epoch_interval=timedelta(0), recovery_config=rc2)
    assert out2 == []
    monkeypatch.setenv("BYTEWAX_TPU_ACCEL", "0")
    run_main(build(inp2, out2), epoch_interval=timedelta(0), recovery_config=rc2)
    assert out2 == [("k", 7.0)]


def test_make_agg_state_selection(monkeypatch):
    from bytewax_tpu.engine.sharded_state import (
        ShardedAggState,
        make_agg_state,
    )
    from bytewax_tpu.engine.xla import DeviceAggState

    _mesh()  # ensure devices exist
    monkeypatch.setenv("BYTEWAX_TPU_SHARD", "0")
    assert isinstance(make_agg_state("sum"), DeviceAggState)
    monkeypatch.setenv("BYTEWAX_TPU_SHARD", "auto")
    st = make_agg_state("sum")
    assert isinstance(st, ShardedAggState)
    assert st.n_shards == 8
    monkeypatch.setenv("BYTEWAX_TPU_SHARD", "4")
    st4 = make_agg_state("sum")
    assert isinstance(st4, ShardedAggState)
    assert st4.n_shards == 4


def test_windowed_fold_sharded_matches_single_device(monkeypatch):
    # The windowed fold table shards over the mesh too: same output
    # as the single-device slot table and the host tier.
    from datetime import datetime, timedelta, timezone

    import bytewax_tpu.operators.windowing as w
    from bytewax_tpu.operators.windowing import EventClock, TumblingWindower
    from tests.test_xla import ArraySource

    _mesh()
    align = datetime(2022, 1, 1, tzinfo=timezone.utc)
    n = 4000
    rng = np.random.RandomState(12)
    secs = np.sort(rng.randint(0, 300, size=n))
    keys = np.array([f"key{k}" for k in rng.randint(0, 6, size=n)])
    vals = (rng.randn(n) * 3).round(2)
    ts = (
        np.datetime64(align.replace(tzinfo=None), "us")
        + secs.astype("timedelta64[s]")
    )

    def run(accel, shard):
        monkeypatch.setenv("BYTEWAX_TPU_ACCEL", accel)
        monkeypatch.setenv("BYTEWAX_TPU_SHARD", shard)
        batches = [
            ArrayBatch(
                {
                    "key": keys[i : i + 512],
                    "ts": ts[i : i + 512],
                    "value": vals[i : i + 512],
                }
            )
            for i in range(0, n, 512)
        ]
        clock = EventClock(
            ts_getter=xla.column_ts,
            wait_for_system_duration=timedelta(seconds=30),
        )
        windower = TumblingWindower(
            length=timedelta(minutes=1), align_to=align
        )
        out = []
        flow = Dataflow("swin_df")
        s = op.input("inp", flow, ArraySource(batches))
        wo = w.reduce_window("sum", s, clock, windower, xla.SUM)
        op.output("out", wo.down, TestingSink(out))
        run_main(flow)
        return sorted(out)

    sharded = run("1", "8")
    single = run("1", "0")
    host = run("0", "0")
    assert [kv[0] for kv in sharded] == [kv[0] for kv in host]
    for (k, (wd, vs)), (_k1, (_w1, v1)), (_k2, (_w2, vh)) in zip(
        sharded, single, host
    ):
        np.testing.assert_allclose(vs, v1, rtol=1e-5, err_msg=k)
        np.testing.assert_allclose(vs, vh, rtol=1e-4, err_msg=k)


def test_sharded_scan_matches_single_device(monkeypatch):
    """ShardedScanState (exchange + per-shard segmented scan +
    outputs home) must produce the same per-row outputs and
    host-format snapshots as DeviceScanState."""
    from bytewax_tpu.engine.scan_accel import DeviceScanState
    from bytewax_tpu.engine.sharded_state import ShardedScanState
    from bytewax_tpu.ops.scan import WelfordZScore
    from bytewax_tpu.parallel.mesh import make_mesh

    rng = np.random.RandomState(17)
    n = 500
    keys = np.array([f"k{j}" for j in rng.randint(0, 13, size=n)])
    vals = rng.randn(n).round(3)

    sh = ShardedScanState(WelfordZScore(2.0), make_mesh(8))
    sd = DeviceScanState(WelfordZScore(2.0))
    t_sh, e_sh = sh.update(keys, vals)
    t_sd, e_sd = sd.update(keys, vals)
    assert sorted(t_sh) == sorted(t_sd)
    np.testing.assert_allclose(e_sh.outs[0], e_sd.outs[0], atol=1e-3)
    np.testing.assert_array_equal(e_sh.outs[1], e_sd.outs[1])
    all_keys = sorted(set(keys.tolist()))
    snaps_sh = dict(sh.snapshots_for(all_keys))
    snaps_sd = dict(sd.snapshots_for(all_keys))
    for k in all_keys:
        (c1, m1, v1), (c2, m2, v2) = snaps_sh[k], snaps_sd[k]
        assert c1 == c2
        assert m1 == pytest.approx(m2, abs=1e-4)
        assert v1 == pytest.approx(v2, abs=1e-3)


def test_sharded_scan_multi_batch_and_growth():
    """Per-key scan order holds across batches and capacity growth:
    fold 3 batches over >cap keys and compare against the host
    mapper oracle."""
    from bytewax_tpu.engine.sharded_state import ShardedScanState
    from bytewax_tpu.ops.scan import WelfordZScore
    from bytewax_tpu.parallel.mesh import make_mesh

    rng = np.random.RandomState(23)
    # cap_per_shard=4 → forces at least one doubling with 80 keys/8 shards.
    st = ShardedScanState(WelfordZScore(2.5), make_mesh(8), cap_per_shard=4)
    mapper = xla.zscore(2.5)
    states, want = {}, collections.defaultdict(list)
    for _b in range(3):
        n = 200
        keys = np.array([f"g{j}" for j in rng.randint(0, 80, size=n)])
        vals = rng.randn(n).round(3)
        _t, emit = st.update(keys, vals)
        got = collections.defaultdict(list)
        for k, (v, z, a) in emit.items():
            got[k].append((v, z, a))
        for k, v in zip(keys.tolist(), vals.tolist()):
            s2, (vv, z, a) = mapper(states.get(k), v)
            states[k] = s2
            want[k].append((vv, z, a))
        # Per-batch per-key emission matches the oracle's tail.
        for k, rows in got.items():
            tail = want[k][-len(rows):]
            for (gv, gz, ga), (wv, wz, wa) in zip(rows, tail):
                assert gv == pytest.approx(wv)
                # f32 fold vs f64 oracle: large |z| (near-degenerate
                # variance) is relatively, not absolutely, accurate.
                assert gz == pytest.approx(wz, rel=1e-3, abs=1e-3)
                assert ga == wa


def test_sharded_scan_resume_from_device_snapshot():
    """Snapshots written by the single-device scan resume into the
    sharded scan (and back) — the cross-tier recovery contract holds
    across mesh sizes."""
    from bytewax_tpu.engine.scan_accel import DeviceScanState
    from bytewax_tpu.engine.sharded_state import ShardedScanState
    from bytewax_tpu.ops.scan import WelfordZScore
    from bytewax_tpu.parallel.mesh import make_mesh

    sd = DeviceScanState(WelfordZScore(2.0))
    sd.update(np.array(["a", "a", "b"]), np.array([1.0, 2.0, 10.0]))
    snaps = [s for s in sd.snapshots_for(["a", "b"])]

    sh = ShardedScanState(WelfordZScore(2.0), make_mesh(8))
    sh.load_many(snaps)
    _t, emit = sh.update(np.array(["a"]), np.array([3.0]))
    mapper = xla.zscore(2.0)
    _s, (_v, z, a) = mapper((2, 1.5, 0.5), 3.0)
    assert emit.outs[0][0] == pytest.approx(z, abs=1e-4)
    assert bool(emit.outs[1][0]) == a
    # And back: sharded snapshots resume on the single-device tier.
    snaps2 = sh.snapshots_for(["a", "b"])
    sd2 = DeviceScanState(WelfordZScore(2.0))
    sd2.load_many(snaps2)
    back = dict(sd2.snapshots_for(["a", "b"]))
    assert back["a"][0] == 3  # count folded the resumed row


def test_make_scan_state_selection(monkeypatch):
    from bytewax_tpu.engine.scan_accel import DeviceScanState
    from bytewax_tpu.engine.sharded_state import (
        ShardedScanState,
        make_scan_state,
    )
    from bytewax_tpu.ops.scan import WelfordZScore

    monkeypatch.setenv("BYTEWAX_TPU_SHARD", "0")
    assert isinstance(make_scan_state(WelfordZScore(2.0)), DeviceScanState)
    monkeypatch.setenv("BYTEWAX_TPU_SHARD", "auto")
    assert isinstance(make_scan_state(WelfordZScore(2.0)), ShardedScanState)


@pytest.mark.parametrize("kind_name", ["ema", "extrema"])
def test_sharded_scan_generic_kinds_match_single_device(kind_name):
    """Kinds WITHOUT a specialized kernel (Ema single-output,
    RunningExtrema multi-output) exercise generic_scan_body inside
    shard_map and the multi-lane return trip — pinned against the
    single-device tier."""
    from bytewax_tpu.engine.scan_accel import DeviceScanState
    from bytewax_tpu.engine.sharded_state import ShardedScanState
    from bytewax_tpu.ops.scan import Ema, RunningExtrema
    from bytewax_tpu.parallel.mesh import make_mesh

    make_kind = (lambda: Ema(0.3)) if kind_name == "ema" else RunningExtrema

    rng = np.random.RandomState(31)
    n = 300
    keys = np.array([f"k{j}" for j in rng.randint(0, 11, size=n)])
    vals = rng.randn(n).round(3)

    sh = ShardedScanState(make_kind(), make_mesh(8))
    sd = DeviceScanState(make_kind())
    t_sh, e_sh = sh.update(keys, vals)
    t_sd, e_sd = sd.update(keys, vals)
    assert sorted(t_sh) == sorted(t_sd)
    assert len(e_sh.outs) == len(e_sd.outs)
    for o_sh, o_sd in zip(e_sh.outs, e_sd.outs):
        np.testing.assert_allclose(o_sh, o_sd, atol=1e-4)
    all_keys = sorted(set(keys.tolist()))
    for (k1, s1), (k2, s2) in zip(
        sh.snapshots_for(all_keys), sd.snapshots_for(all_keys)
    ):
        assert k1 == k2
        np.testing.assert_allclose(s1, s2, rtol=1e-4, atol=1e-5)
