"""BTX-LANE positive fixture: an un-cataloged lane.

The module is otherwise disciplined — the lane uses a cataloged
ledger phase and the module drains it (flush + shutdown) — so the
ONE finding is the catalog-closure violation: a ``DevicePipeline``
construction site no ``contracts.LANES`` entry names.  A new ordered
off-main-thread lane must never appear silently.
"""

from bytewax_tpu.engine.pipeline import DevicePipeline


class SneakyStep:
    def __init__(self):
        self._pipe = DevicePipeline("sneaky", depth=2, phase="device")

    def process(self, port, entries):
        def task():
            return entries

        def finalize(res):
            pass

        self._pipe.push(task, finalize)

    def finalize(self):
        self._pipe.flush()
        self._pipe.shutdown()
