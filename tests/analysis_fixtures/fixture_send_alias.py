"""BTX-SEND positive fixture: an alias-smuggled raw send.

``c = self.comm`` then ``c.send(...)`` never puts a ``comm``-named
receiver on the call line, so the regex scan this analyzer replaced
(``_RAW_SEND_STRICT`` in the old tests/test_comm_invariants.py)
provably missed it — the resolver's alias tracking must not.
"""


class RogueOperator:
    def __init__(self, driver):
        self.comm = driver.comm

    def process(self, port, entries):
        c = self.comm
        shipper = c
        for w, items in entries:
            # An uncounted data frame: breaks the epoch barrier's
            # count-matched quiescence check.
            shipper.send(w, ("deliver", 0, "up", (w, items)))
