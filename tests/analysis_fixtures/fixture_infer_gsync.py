"""BTX-GSYNC positive fixture: an inference runtime agreeing a params
swap per delivery.

Swap agreement is an epoch-close concern — the pending-params vote
rides the existing "fstat" gsync payload at the globally-ordered
close.  This runtime instead enters a sync round from ``update`` (a
per-batch surface), hidden behind a helper AND a bound-method alias
so no line spells the primitive as a call — yet any peer that did
not receive the same delivery deadlocks in the rogue round.
"""


class EagerSwapInfer:
    def __init__(self, driver):
        self.driver = driver
        self.generation = 0

    def _agree_swap(self, digest):
        vote = self.driver.global_sync
        return vote(("swap-round", self.generation), digest)

    def update(self, keys, values):
        self._agree_swap(len(keys))
        return []
