"""BTX-RACE positive fixture: a worker/main shared attribute
smuggled through a bound-method alias.

The task handed to ``DevicePipeline.push`` runs on the worker
thread; it binds ``self._bump`` to a local first, so no line inside
the task ever spells ``self.<anything> = ...`` — only callable
tracing into the submission plus bound-method alias resolution can
see that the worker lane WRITES ``self._tally`` while the per-batch
main path reads it to route.  The attribute is pinned in no
``SHARED_STATE`` inventory, so the finding must carry BOTH witness
chains (the worker path through the alias and the main read path).
"""

from bytewax_tpu.engine.pipeline import DevicePipeline


class RacyStep:
    def __init__(self):
        self._pipe = DevicePipeline("racy", depth=2, phase="device")
        self._tally = 0

    def _bump(self, n):
        # The worker-side write: reached only through the alias.
        self._tally = self._tally + n

    def process(self, port, entries):
        # The main-side read: per-batch routing keyed on the tally.
        lane = self._tally % 2

        def task():
            bump = self._bump
            bump(len(entries))
            return entries, lane

        def finalize(res):
            pass

        self._pipe.push(task, finalize)

    def finalize(self):
        self._pipe.flush()
        self._pipe.shutdown()
