"""BTX-FRAMES positive fixture: a frame kind outside the pinned
inventory, both handled and sent."""


class RogueDriver:
    def __init__(self, comm):
        self.comm = comm

    def _handle_ctrl(self, _src, msg):
        kind = msg[0]
        if kind == "deliver":
            pass
        elif kind == "rogue_frame":  # not in CONTROL_FRAMES
            pass

    def announce(self):
        self.comm.broadcast(("rogue_frame", 42))
