"""BTX-LANE positive fixture: a lane constructed under a ledger
phase no catalog entry pins.

The phase string at the construction site decides which ledger
bucket the lane's seconds land in — ``derive_rescale_hint``'s
fraction signals are only as honest as those buckets.  A lane that
invents its own phase name silently bleeds its wall time into a
bucket no observer knows to read (docs/observability.md's phase
table lists exactly the cataloged phases).
"""

from bytewax_tpu.engine.pipeline import DevicePipeline


class MisbucketedStep:
    def __init__(self):
        self._pipe = DevicePipeline("turbo", depth=2, phase="turbo_lane")

    def process(self, port, entries):
        def task():
            return entries

        def finalize(res):
            pass

        self._pipe.push(task, finalize)

    def finalize(self):
        self._pipe.flush()
        self._pipe.shutdown()
