"""BTX-SNAPSHOT positive fixtures for the residency pairing: a
device-tier state class reachable from a dispatch-table factory that
implements ``extract_keys`` with no ``inject_keys`` (stranded
evictions), and a ``global_exchange = True`` tier that implements the
residency surface at all (per-process eviction would desynchronize
the collective step shapes)."""


class HalfResidentState:
    """Evicts but cannot restore: extract_keys with no inject_keys."""

    def demotion_snapshots(self):
        return []

    def extract_keys(self, keys):
        return [(k, None) for k in keys]

    def update(self, keys, values):
        return []


class EvictingGlobalState:
    """Collective tier that wrongly exposes the residency surface."""

    global_exchange = True

    def extract_keys(self, keys):
        return []

    def inject_keys(self, items):
        pass


class HalfResidentSpec:
    def make_state(self):
        return HalfResidentState()


def make_agg_state(kind):
    return EvictingGlobalState()
