"""BTX-DRAIN positive fixture: an eviction reachable from a per-batch
path.

``process`` -> ``_maybe_trim`` -> ``evict_to_budget`` never passes
through a drain point, so a deferred fold still in flight on the
pipeline worker could reference the slot this eviction reclaims — the
exact single-schedule race the drain-point discipline exists to
prevent.  Also exercises the pipeline-receiver drain seed: a raw
``flush()`` on a ``DevicePipeline`` from a per-batch helper.
"""

from bytewax_tpu.engine.pipeline import DevicePipeline


class TinyManager:
    def __init__(self, budget):
        self.budget = budget
        self.resident = {}

    def over_budget(self):
        return len(self.resident) > self.budget

    def evict_to_budget(self, epoch):
        while self.over_budget():
            self.resident.popitem()


class EagerStep:
    def __init__(self):
        self.res = TinyManager(64)
        self.pipe = DevicePipeline("eager")

    def process(self, port, entries):
        self._fold(entries)
        self._maybe_trim()

    def _fold(self, entries):
        for _w, items in entries:
            self.pipe.push(lambda: items, lambda res: None)

    def _maybe_trim(self):
        # Per-batch eviction with NO pipeline quiesce first: flagged.
        if self.res.over_budget():
            self.res.evict_to_budget(0)

    def on_batch(self, items):
        # Per-batch raw pipeline drain (not at a drain point): the
        # worker-owned fold structures are read mid-stream.
        self.pipe.flush()
        return items


class UnflushedSyncStep:
    def __init__(self, driver):
        self.driver = driver

    def pre_close(self):
        # Gsync round with NO pipeline flush first — and the
        # primitive hides behind a bound-method alias, so only the
        # alias-aware flush-before-sync check can see it.
        gs = self.driver.global_sync
        gs("tag", None)
