"""BTX-THREAD positive fixture: a worker-lane task that aliases its
way to the raw cluster send surface.

The task handed to ``DevicePipeline.push`` runs on the pipeline's
worker thread; binding the bound send method to a local first means
no line ever spells a literal receiver-dot-send call — only
callable-argument tracing into the thread submission plus
bound-method alias resolution can see that the worker lane reaches
the send surface.
"""

from bytewax_tpu.engine.comm import Comm
from bytewax_tpu.engine.pipeline import DevicePipeline


class LeakyStep:
    def __init__(self, listen, peers, proc_id):
        self.comm = Comm(listen, peers, proc_id)
        self._pipe = DevicePipeline("leaky")

    def process(self, port, entries):
        def task():
            # A "helpful" progress report from the device phase: an
            # uncounted frame sent OFF the main thread — exactly the
            # race/protocol violation BTX-THREAD exists to catch.
            s = self.comm.send
            s(0, ("report_msg", len(entries)))
            return entries

        def finalize(res):
            pass

        self._pipe.push(task, finalize)
