"""Waiver fixture: the same raw-send shape as fixture_send_alias,
suppressed by an inline ``# bytewax: allow[...]`` waiver — and a
string literal containing ``#`` that must NOT hide the call from the
analyzer (the old line-split comment stripping truncated here)."""


class WaivedOperator:
    def __init__(self, driver):
        self.comm = driver.comm

    def emergency_flush(self, w, items):
        # A sanctioned, documented exception would be waived like so:
        self.comm.send(w, ("deliver", 0, "up", (w, items)))  # bytewax: allow[BTX-SEND]

    def tagged_flush(self, w, items):
        tag = "#deliver"  # a '#' in a string is not a comment
        self.comm.send(w, (tag, items))  # bytewax: allow[BTX-SEND,BTX-FRAMES]
