"""BTX-GSYNC positive fixture: a collective reachable from a
per-batch path.

The sync round hides behind a helper AND a bound-method alias, so no
line matches the old ``global_sync\\s*\\(`` regex outside an
allowlisted file — yet ``process`` (a per-batch surface) transitively
enters a collective sync round, which deadlocks every peer that did
not receive the same delivery.
"""


class EagerExchange:
    def __init__(self, driver):
        self.driver = driver

    def _sync_now(self, payload):
        do_sync = self.driver.global_sync
        return do_sync(("rogue-round", 0), payload)

    def process(self, port, entries):
        for _w, items in entries:
            self._sync_now(len(items))
