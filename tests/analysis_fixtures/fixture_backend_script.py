"""BTX-BACKEND positive fixture: a standalone script that starts the
engine with no backend forced first."""

from bytewax_tpu.dataflow import Dataflow

flow = Dataflow("fixture")

if __name__ == "__main__":
    from bytewax_tpu.testing import run_main

    run_main(flow)
