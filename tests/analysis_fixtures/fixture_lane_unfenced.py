"""BTX-LANE positive fixture: an un-fenced lane.

The module constructs a ``DevicePipeline`` and flushes it on the hot
path, but NOTHING in the module ever calls ``.shutdown()`` or
``.drop_pending()`` on a pipeline-denoting receiver — at teardown the
worker thread is abandoned with whatever it still holds.  The
module-local drain check fires on fixtures too (the tree half of the
fence proof additionally demands reachability from the pinned
run-ending closes).
"""

from bytewax_tpu.engine.pipeline import DevicePipeline


class ForgetfulStep:
    def __init__(self):
        self._pipe = DevicePipeline("forgetful", depth=2, phase="device")

    def process(self, port, entries):
        def task():
            return entries

        def finalize(res):
            pass

        self._pipe.push(task, finalize)

    def drain(self):
        # Flushes in-flight work... and then never tears down.
        self._pipe.flush()
