"""BTX-SNAPSHOT positive fixture: a device-tier state class reachable
from a dispatch-table factory with no ``demotion_snapshots()``."""


class OrphanDeviceState:
    """No demotion_snapshots and not global_exchange: demotion would
    strand this state on a faulted device."""

    def update(self, keys, values):
        return []


class OrphanAccelSpec:
    def make_state(self):
        return OrphanDeviceState()
