"""BTX-FAULT positive fixture: an unknown fault site, and a device
mutation ordered before the fire."""

from bytewax_tpu.engine import faults as _faults


class SloppyDispatch:
    def _process_device(self, entries):
        pass

    def dispatch(self, entries):
        # Unknown site: evades the pinned inventory.
        _faults.fire("device_dispatchx", step="s")

    def dispatch_late_fire(self, entries):
        # Mutates device state BEFORE the fault site: a DeviceFault
        # raised here would not be retryable.
        self._process_device(entries)
        _faults.fire("device_dispatch", step="s")
