"""BTX-FAULT positive fixture: an unknown fault site, and a device
mutation ordered before the fire."""

from bytewax_tpu.engine import faults as _faults


class SloppyDispatch:
    def _process_device(self, entries):
        pass

    def dispatch(self, entries):
        # Unknown site: evades the pinned inventory.
        _faults.fire("device_dispatchx", step="s")

    def dispatch_late_fire(self, entries):
        # Mutates device state BEFORE the fault site: a DeviceFault
        # raised here would not be retryable.
        self._process_device(entries)
        _faults.fire("device_dispatch", step="s")

    def _spin_helper(self, entries):
        # Not itself a mutator name — but reaches one.
        self._process_device(entries)

    def dispatch_hidden_mutation(self, entries):
        # The mutation hides one call-graph hop away (the dispatch-
        # pipeline indirection shape): only the reachability walk
        # sees it.
        self._spin_helper(entries)
        _faults.fire("device_dispatch", step="s")
