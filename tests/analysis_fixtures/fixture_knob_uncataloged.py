"""BTX-KNOB positive fixture: an uncataloged knob read plus a
computed knob name.

``BYTEWAX_TPU_TURBO`` exists nowhere in ``contracts.KNOBS`` — a knob
shipped without inventory or docs.  The f-string read can never be
matched against the catalog at all, so it is flagged as a computed
knob name regardless of what it expands to.
"""

import os


def turbo_enabled() -> bool:
    return os.environ.get("BYTEWAX_TPU_TURBO", "0") == "1"


def shard_override(n: int) -> str:
    return os.environ.get(f"BYTEWAX_TPU_SHARD_{n}", "")


def subscript_read() -> str:
    # Subscript loads are reads too.
    return os.environ["BYTEWAX_TPU_SECRET_MODE"]


_KNOB = "BYTEWAX_TPU_STEALTH_MODE"


def indirect_read() -> str:
    # One level of variable indirection cannot slip the catalog.
    return os.environ.get(_KNOB, "0")
