"""BTX-SNAPSHOT positive fixture: an inference broadcast-params state
class reachable from a spec factory with no ``demotion_snapshots()``.

The streaming-inference subsystem (docs/inference.md) keeps the params
pytree as broadcast device state; on repeated DeviceFault the step
demotes to the host apply, which only works if the state class can
drain the params row (and its swap generation) as host-format
snapshots.  This one can't — demotion would strand the broadcast
params on the faulted device.
"""


class BroadcastParamsState:
    """Batched forward pass over a broadcast params pytree; scores
    flow per-delivery but the params generation never drains
    host-side."""

    def __init__(self, params):
        self.params = params
        self.generation = 0

    def install_params(self, params, generation):
        self.params = params
        self.generation = generation

    def update(self, keys, values):
        return []


class EagerInferSpec:
    def make_state(self):
        return BroadcastParamsState({"w": 1.0})
