"""Pallas segment-fold kernel: equivalence with the XLA scatter path
(interpret mode on the CPU backend)."""

import numpy as np
import pytest

import jax.numpy as jnp

from bytewax_tpu.ops.pallas_fold import update_fields_pallas
from bytewax_tpu.ops.segment import AGG_KINDS, init_fields, update_fields


@pytest.mark.parametrize("kind_name", ["sum", "count", "min", "max", "stats"])
def test_pallas_matches_scatter(kind_name):
    kind = AGG_KINDS[kind_name]
    capacity = 128
    rng = np.random.RandomState(0)
    n = 1000
    padded = 1024
    slots = np.full(padded, capacity - 1, dtype=np.int32)
    slots[:n] = rng.randint(0, capacity - 1, size=n)
    vals = np.zeros(padded, dtype=np.float32)
    vals[:n] = rng.randn(n).astype(np.float32)

    ref = update_fields(
        kind, init_fields(kind, capacity), jnp.asarray(slots), jnp.asarray(vals)
    )
    got = update_fields_pallas(
        kind, init_fields(kind, capacity), jnp.asarray(slots), jnp.asarray(vals)
    )
    for name in kind.fields:
        np.testing.assert_allclose(
            np.asarray(got[name]),
            np.asarray(ref[name]),
            rtol=1e-5,
            atol=1e-5,
            err_msg=f"{kind_name}/{name}",
        )


def test_pallas_engine_end_to_end(monkeypatch):
    monkeypatch.setenv("BYTEWAX_TPU_PALLAS", "1")
    monkeypatch.setenv("BYTEWAX_TPU_ACCEL", "1")
    import bytewax_tpu.operators as op
    from bytewax_tpu.dataflow import Dataflow
    from bytewax_tpu.testing import TestingSink, TestingSource, run_main

    inp = ["apple", "banana", "apple", "banana", "banana"]
    out = []
    flow = Dataflow("test_df")
    s = op.input("inp", flow, TestingSource(inp))
    s = op.count_final("count", s, lambda x: x)
    op.output("out", s, TestingSink(out))
    run_main(flow)
    assert sorted(out) == [("apple", 2), ("banana", 3)]


def test_pallas_int_state_falls_back_to_exact_scatter(monkeypatch):
    # Integer accumulators must keep exact scatter semantics even with
    # the Pallas kernel enabled (f32 masks round above 2^24).
    monkeypatch.setenv("BYTEWAX_TPU_PALLAS", "1")
    from bytewax_tpu.engine.xla import DeviceAggState

    agg = DeviceAggState("sum")
    big = 20_000_001  # not representable in f32
    agg.update(np.array(["k"]), np.array([big], dtype=np.int32))
    agg.update(np.array(["k"]), np.array([big], dtype=np.int32))
    assert dict(agg.finalize())["k"] == 2 * big
